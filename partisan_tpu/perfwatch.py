"""Runtime performance observatory — measured phase attribution,
dispatch-wall decomposition, measured-vs-predicted reconciliation and
the bench-history ledger (ISSUE 16).

Everything in here is HOST-SIDE ONLY.  The module never adds a traced
eqn to any round program: phase attribution parses ``jax.profiler``
trace captures *after* the fact, the dispatch meter brackets existing
``block_until_ready``-style syncs with ``time.perf_counter``, and the
ledger is pure JSON bookkeeping.  tests/test_perfwatch.py asserts the
zero-traced-eqns guarantee through the existing lint matrix.

Four pieces:

* **Phase attribution** (`capture`, `attribute`) — a minimal protobuf
  wire-format reader (no TF dependency) joins the op-level events in
  ``<host>.trace.json.gz`` against the HloProto op metadata embedded in
  ``<host>.xplane.pb`` to recover the ``round.*`` named_scope each HLO
  op came from — the SAME phase keys `lint/cost.py` predicts with and
  the zero-cost lint rule gates on.  Works on CPU with the exact code
  path an on-chip session will use.
* **Dispatch-wall meter** (`dispatch_timeline`, `decompose`,
  `decompose_chunks`, `pipeline_probe`) — submit→ready bracketing that
  splits a chunked run into in-execution time vs dispatch gap, plus a
  double-buffered-dispatch probe quantifying ROADMAP item 1(b)
  headroom.
* **Reconciliation** (`reconcile`) — joins measured phase ms against
  the cost-meter census to compute effective bytes/s per phase and
  flag outliers: the machine-generated VMEM-fusion target list for
  ROADMAP item 1(a).
* **Bench-history ledger** (`artifact_rows`, `append_rows`,
  `ledger_deltas`) — append-only JSON-lines keyed by
  (kind, n, config, host fingerprint); deltas vs the best prior
  comparable entry; regression beyond a band is a hard failure.
"""

from __future__ import annotations

import contextlib
import glob
import gzip
import json
import os
import time
from typing import Any, Callable, Iterator

# --------------------------------------------------------------------
# protobuf wire format (reader + just-enough writer)
#
# The profiler artifacts are protobufs but the container has no
# tensorflow/protobuf-compiled schema for them; the wire format itself
# is trivial.  Field numbers below were verified against jax 0.4.37
# CPU captures (tests round-trip them through `_encode_field`).
# --------------------------------------------------------------------


def _varint(buf: bytes, i: int) -> tuple[int, int]:
    r = s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s
        s += 7
        if not b & 0x80:
            return r, i


def _fields(buf: bytes) -> Iterator[tuple[int, int, Any]]:
    """Yield (field_number, wire_type, value) over one message."""
    i = 0
    n = len(buf)
    while i < n:
        key, i = _varint(buf, i)
        fn, wt = key >> 3, key & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        else:  # pragma: no cover - groups don't appear in profiler pbs
            raise ValueError(f"unsupported wire type {wt}")
        yield fn, wt, v


def _encode_varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _encode_field(fn: int, value) -> bytes:
    """Encode one field: int -> varint, bytes/str -> length-delimited."""
    if isinstance(value, int):
        return _encode_varint(fn << 3 | 0) + _encode_varint(value)
    if isinstance(value, str):
        value = value.encode()
    return _encode_varint(fn << 3 | 2) + _encode_varint(len(value)) + value


# --------------------------------------------------------------------
# HloProto scope map: (module, op) -> named_scope op_name
# --------------------------------------------------------------------

# XSpace.planes=1; XPlane{name=2, event_metadata map=4,
# stat_metadata map=5, stats=6}; map entry{key=1, value=2};
# XEventMetadata{id=1, name=2, stats=5};
# XStat{metadata_id=1, bytes_value=6}; XStatMetadata{id=1, name=2};
# HloProto{hlo_module=1}; HloModuleProto{name=1, computations=3};
# HloComputationProto{instructions=2};
# HloInstructionProto{name=1, metadata=7}; OpMetadata{op_name=2}.
# On jax 0.4.x CPU the HloProto rides the "/host:metadata" plane as an
# XStat (metadata name "Hlo Proto", bytes_value) attached to each
# module's XEventMetadata entry.


def _norm_module(name: str) -> str:
    """``jit_steps(3)`` and ``jit_steps`` are the same module — the
    ``(id)`` suffix differs between the xplane metadata plane and the
    trace.json ``hlo_module`` arg."""
    return name.split("(")[0]


def hlo_scope_map(xplane: bytes) -> dict[tuple[str, str], str]:
    """Parse an ``.xplane.pb`` into ``{(module, op_name): scope_path}``.

    The scope path is the full ``jit(f)/.../round.phase/op`` metadata
    op_name XLA records per instruction; `phase_of_op_name` extracts
    the ``round.*`` segment from it.
    """
    out: dict[tuple[str, str], str] = {}
    for fn, _wt, plane in _fields(xplane):
        if fn != 1:
            continue
        name = b""
        stat_names: dict[int, bytes] = {}
        stats: list[bytes] = []
        for pfn, _pwt, pv in _fields(plane):
            if pfn == 2:
                name = pv
            elif pfn == 4:  # event_metadata map entry -> XEventMetadata
                for efn, _ewt, ev in _fields(pv):
                    if efn != 2:
                        continue
                    for mfn, _mwt, mv in _fields(ev):
                        if mfn == 5:  # XEventMetadata.stats
                            stats.append(mv)
            elif pfn == 5:  # stat_metadata map entry
                k = v = None
                for efn, _ewt, ev in _fields(pv):
                    if efn == 1:
                        k = ev
                    elif efn == 2:
                        v = ev
                if k is not None and v is not None:
                    for mfn, _mwt, mv in _fields(v):
                        if mfn == 2:
                            stat_names[k] = mv
            elif pfn == 6:
                stats.append(pv)
        if b"metadata" not in name:
            continue
        hlo_ids = {k for k, v in stat_names.items() if v == b"Hlo Proto"}
        for st in stats:
            mid, blob = None, None
            for sfn, _swt, sv in _fields(st):
                if sfn == 1:
                    mid = sv
                elif sfn == 6:
                    blob = sv
            if mid not in hlo_ids or blob is None:
                continue
            for hfn, _hwt, hv in _fields(blob):
                if hfn != 1:  # HloProto.hlo_module
                    continue
                mod_name = ""
                for m_fn, _m_wt, m_v in _fields(hv):
                    if m_fn == 1:
                        mod_name = _norm_module(m_v.decode())
                    elif m_fn == 3:  # computations
                        for c_fn, _c_wt, c_v in _fields(m_v):
                            if c_fn != 2:  # instructions
                                continue
                            op = scope = ""
                            for ifn, _iwt, iv in _fields(c_v):
                                if ifn == 1:
                                    op = iv.decode()
                                elif ifn == 7:  # OpMetadata
                                    for ofn, _owt, ov in _fields(iv):
                                        if ofn == 2:
                                            scope = ov.decode()
                            if op and scope:
                                out[(mod_name, op)] = scope
    return out


def phase_of_op_name(op_name: str) -> str:
    """Extract the ``round.*`` named_scope segment from an XLA metadata
    op_name — the same rule `lint/cost.py` applies to jaxpr eqn
    source_info, so measured and predicted tables share keys.  Ops with
    no round scope land in the ``"-"`` bucket, matching the census's
    unphased bucket."""
    for seg in op_name.split("/"):
        if seg.startswith("round."):
            return seg
    return "-"


# --------------------------------------------------------------------
# trace.json op events + capture discovery
# --------------------------------------------------------------------


def trace_events(path: str) -> list[dict]:
    """Load device op events (have hlo_op/hlo_module args and a µs
    duration) from a ``.trace.json.gz``."""
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rt") as f:
        doc = json.load(f)
    out = []
    for ev in doc.get("traceEvents", []):
        args = ev.get("args") or {}
        if ev.get("ph") == "X" and "hlo_op" in args and "hlo_module" in args:
            out.append({"module": _norm_module(args["hlo_module"]),
                        "op": args["hlo_op"],
                        "dur_us": float(ev.get("dur", 0))})
    return out


def find_capture(trace_dir: str) -> tuple[str, str] | None:
    """Newest (xplane.pb, trace.json.gz) pair under a profiler dir."""
    runs = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*")))
    for run in reversed(runs):
        xs = sorted(glob.glob(os.path.join(run, "*.xplane.pb")))
        ts = sorted(glob.glob(os.path.join(run, "*.trace.json.gz")))
        if xs and ts:
            return xs[0], ts[0]
    return None


def attribute(trace_dir: str) -> dict[str, dict]:
    """Collapse the newest capture under ``trace_dir`` into
    ``{phase: {"ms": float, "events": int}}`` over ``round.*`` phases
    (plus ``"-"`` for unattributed device time)."""
    pair = find_capture(trace_dir)
    if pair is None:
        return {}
    xplane_path, trace_path = pair
    with open(xplane_path, "rb") as f:
        scopes = hlo_scope_map(f.read())
    phases: dict[str, dict] = {}
    for ev in trace_events(trace_path):
        scope = scopes.get((ev["module"], ev["op"]), "")
        ph = phase_of_op_name(scope)
        slot = phases.setdefault(ph, {"ms": 0.0, "events": 0})
        slot["ms"] += ev["dur_us"] / 1000.0
        slot["events"] += 1
    for slot in phases.values():
        slot["ms"] = round(slot["ms"], 4)
    return phases


@contextlib.contextmanager
def capture(trace_dir: str | None = None):
    """Profiler capture scoped to a ``with`` block.

    ``trace_dir`` falls back to the ``PROFILE_TRACE_DIR`` env var (the
    tools/profile_round.py convention); with neither set this is a
    no-op yielding None, so call sites stay unconditional.  Yields the
    directory to attribute() afterwards.

    Uses a raw ProfilerSession with the PYTHON TRACER OFF instead of
    ``jax.profiler.trace``: jax's default (python_tracer_level=1)
    floods long captures with per-call host events, and the
    trace.json export caps at ~1M events — the device op events
    attribution needs were the ones truncated away.  Device + runtime
    tracing (host_tracer_level=2, hlo_proto on) is unchanged; falls
    back to ``jax.profiler.trace`` if the raw API moves.
    """
    trace_dir = trace_dir or os.environ.get("PROFILE_TRACE_DIR")
    if not trace_dir:
        yield None
        return
    import jax

    try:
        from jax._src.lib import xla_client

        opts = xla_client.profiler.ProfileOptions()
        opts.python_tracer_level = 0
        opts.enable_hlo_proto = True
        jax.devices()  # init the backend before the tracer attaches
        sess = xla_client.profiler.ProfilerSession(opts)
    except Exception:
        with jax.profiler.trace(trace_dir):
            yield trace_dir
        return
    try:
        yield trace_dir
    finally:
        sess.export(sess.stop(), str(trace_dir))


# --------------------------------------------------------------------
# dispatch-wall meter
# --------------------------------------------------------------------


def dispatch_timeline(step: Callable, sync: Callable, state,
                      *, chunks: int, k: int) -> tuple[list[dict], Any]:
    """Run ``chunks`` × ``step(state, k)`` with submit→ready bracketing.

    Returns (records, final_state); each record has ``submit_t``,
    ``ready_t`` and ``gap_s`` (host time between the previous chunk's
    ready and this chunk's submit — pure dispatch overhead, no device
    work in flight)."""
    records = []
    prev_ready = None
    for _ in range(chunks):
        submit = time.perf_counter()
        state = step(state, k)
        sync(state)
        ready = time.perf_counter()
        records.append({
            "submit_t": submit, "ready_t": ready, "k": k,
            "wall_s": ready - submit,
            "gap_s": None if prev_ready is None else submit - prev_ready,
        })
        prev_ready = ready
    return records, state


def decompose(records: list[dict]) -> dict:
    """Split a timeline into in-execution vs dispatch-gap time.

    Pipelined rows (soak's ``pipeline_depth >= 2``) carry ``busy_s``
    — the ready-to-ready execution span — because their ``wall_s``
    includes queue wait behind the previous in-flight chunk, and
    double-counting the overlap would inflate in-execution time past
    the wall clock.  Their gaps are already clamped to true stalls
    (zero when the device never idled), so the gap column keeps
    meaning "device waited on the host" in both regimes."""
    rows = [r for r in records if r.get("wall_s") is not None]
    if not rows:
        return {}
    exec_s = sum(r["busy_s"] if r.get("busy_s") is not None
                 else r["wall_s"] for r in rows)
    # Telemetry-spool drains (soak rows' ``spool_s``) run between a
    # chunk's ready and the NEXT submit, so they land inside the next
    # row's gap_s — attribute that host time to its own column instead
    # of letting collection cost masquerade as dispatch wall.
    gaps = []
    spool_s = 0.0
    prev_spool = None
    for r in rows:
        if r.get("gap_s") is not None:
            g = max(0.0, r["gap_s"])
            if prev_spool:
                sp = min(float(prev_spool), g)
                spool_s += sp
                g -= sp
            gaps.append(g)
        prev_spool = r.get("spool_s")
    if prev_spool:
        # the last row's drain happened after its ready too — no later
        # gap absorbs it, but it is still spool host time
        spool_s += float(prev_spool)
    gap_s = sum(gaps)
    total = exec_s + gap_s + spool_s
    out = {
        "chunks": len(rows),
        "in_execution_s": round(exec_s, 4),
        "gap_s": round(gap_s, 4),
        "gap_share": round(gap_s / total, 4) if total > 0 else 0.0,
        "per_chunk_gap_ms": (round(1000.0 * gap_s / len(gaps), 3)
                             if gaps else None),
    }
    if spool_s > 0:
        out["spool_s"] = round(spool_s, 4)
    overlapped = sum(1 for r in rows if r.get("pipelined"))
    if overlapped:
        out["overlapped_chunks"] = overlapped
    return out


def decompose_chunks(chunks: list[dict]) -> dict:
    """`decompose` over soak.run_chunked chunk rows (their ``wall_s`` /
    ``gap_s`` fields are already submit→ready brackets; pipelined rows
    pass ``busy_s``/``pipelined`` through for the overlapped regime)."""
    return decompose([
        {"wall_s": c.get("wall_s"), "gap_s": c.get("gap_s"),
         "busy_s": c.get("busy_s"), "pipelined": c.get("pipelined"),
         "spool_s": c.get("spool_s")}
        for c in chunks if isinstance(c, dict) and "wall_s" in c])


def pipeline_probe(step: Callable, sync: Callable, state,
                   *, reps: int = 6, k: int = 10) -> tuple[dict, Any]:
    """Measure double-buffered dispatch headroom (ROADMAP item 1(b)).

    Serial: submit+sync each chunk (today's soak loop).  Pipelined:
    chain ``reps`` dispatches and sync once — JAX's async dispatch
    overlaps submit with execution.  ``overlap`` is the measured share
    of serial wall the chaining recovers."""
    # warm both paths so neither pays compile
    state = step(state, k)
    sync(state)

    t0 = time.perf_counter()
    for _ in range(reps):
        state = step(state, k)
        sync(state)
    serial_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(reps):
        state = step(state, k)
    sync(state)
    pipelined_s = time.perf_counter() - t0

    return {
        "reps": reps, "k": k,
        "serial_s": round(serial_s, 4),
        "pipelined_s": round(pipelined_s, 4),
        "overlap": (round(max(0.0, 1.0 - pipelined_s / serial_s), 4)
                    if serial_s > 0 else 0.0),
        "saved_ms_per_chunk": round(
            1000.0 * max(0.0, serial_s - pipelined_s) / reps, 3),
    }, state


# --------------------------------------------------------------------
# measured-vs-predicted reconciliation
# --------------------------------------------------------------------


def reconcile(measured: dict[str, dict], census, *, rounds: int = 1,
              outlier_x: float = 3.0) -> list[dict]:
    """Join a measured phase table against a `lint.cost.Census`.

    One row per census phase (so the key sets match by construction —
    the acceptance gate), carrying measured ms, predicted footprint
    bytes (interm + 4·fetched words, per round × ``rounds`` executed
    under capture), effective bytes/s, and an ``outlier`` flag: a
    phase whose share of measured time exceeds ``outlier_x`` × its
    share of predicted bytes (with a small absolute-time floor so µs
    phases don't flag).  Outliers are the VMEM-fusion target list for
    ROADMAP item 1(a)."""
    phases = dict(census.phases)
    meas = dict(measured)
    total_ms = sum(m.get("ms", 0.0) for m in meas.values()) or 0.0

    def footprint(pc) -> int:
        return int(pc.interm_bytes + 4 * pc.fetched)

    total_bytes = sum(footprint(pc) for pc in phases.values()) or 0
    rows = []
    for name in sorted(phases):
        pc = phases[name]
        m = meas.get(name, {})
        ms = float(m.get("ms", 0.0))
        fp = footprint(pc) * max(1, rounds)
        row = {
            "phase": name,
            "measured_ms": round(ms, 4),
            "events": int(m.get("events", 0)),
            "predicted_bytes": fp,
            "gathers": int(pc.gathers),
            "scatters": int(pc.scatters),
            "eqns": int(pc.eqns),
            "eff_bytes_per_s": (round(fp / (ms / 1000.0))
                                if ms > 0 else None),
        }
        time_share = ms / total_ms if total_ms > 0 else 0.0
        byte_share = fp / (total_bytes * max(1, rounds)) \
            if total_bytes > 0 else 0.0
        row["time_share"] = round(time_share, 4)
        row["outlier"] = bool(
            ms >= 0.05 * total_ms and total_ms > 0
            and time_share > outlier_x * max(byte_share, 1e-12))
        rows.append(row)
    # device time attributed to ops outside every census phase (e.g.
    # capture-scope injections) — keep it visible without inventing a
    # key the census lacks, unless the census itself has "-".
    extra = {k: v for k, v in meas.items() if k not in phases}
    if extra:
        ms = sum(v.get("ms", 0.0) for v in extra.values())
        rows.append({"phase": "(unattributed)",
                     "measured_ms": round(ms, 4),
                     "events": sum(int(v.get("events", 0))
                                   for v in extra.values()),
                     "predicted_bytes": 0, "gathers": 0, "scatters": 0,
                     "eqns": 0, "eff_bytes_per_s": None,
                     "time_share": round(ms / total_ms, 4)
                     if total_ms > 0 else 0.0,
                     "outlier": False})
    return rows


# --------------------------------------------------------------------
# bench-history ledger
# --------------------------------------------------------------------

LEDGER_DEFAULT = "BENCH_LEDGER.jsonl"
# Standing states documented in BENCH_NOTES.md: the relay still blocks
# Pallas lowering, and the ~60 s fault-repro wall still stands.  Rows
# record them per run so the prose stops being the source of truth;
# override per-ingest once either falls.
PALLAS_DEFAULT = "BLOCKED"
MINUTE_WALL_DEFAULT = "STANDING"


def host_fingerprint() -> str:
    """Fingerprint live runs by backend platform — ledger deltas only
    compare within one fingerprint (a CPU run regressing vs a TPU run
    is noise, not signal)."""
    import jax

    return jax.default_backend()


def _tail_host(tail: str) -> str:
    t = tail or ""
    for plat in ("axon", "tpu", "gpu", "cpu"):
        if f"Platform '{plat}'" in t or f"platform: {plat}" in t:
            return plat
    return "unknown"


def doc_rows(doc: dict, source: str, *, pallas: str | None = None,
             minute_wall: str | None = None) -> list[dict]:
    """Flatten one bench artifact (BENCH_r*.json / MULTICHIP_r*.json /
    a live bench.py result doc) into ledger rows."""
    pallas = pallas or PALLAS_DEFAULT
    minute_wall = minute_wall or MINUTE_WALL_DEFAULT
    rows: list[dict] = []

    if "n_devices" in doc:  # MULTICHIP probe artifact
        rows.append({"kind": "multichip", "source": source,
                     "n_devices": int(doc["n_devices"]),
                     "ok": bool(doc.get("ok")),
                     "skipped": bool(doc.get("skipped")),
                     "host": _tail_host(doc.get("tail", ""))})
        return rows

    parsed = doc.get("parsed") or doc
    host = _tail_host(doc.get("tail", "")) \
        if "tail" in doc else host_fingerprint()
    probe = doc.get("pallas_probe") or {}
    if isinstance(probe, dict) and probe.get("verdict"):
        pallas = probe["verdict"]

    # Superstep runs (bench.py --superstep R) are keyed as their own
    # config: R rounds fused per scan step changes what one execution
    # means, so deltas/--check must only ever compare like-for-like —
    # a fused run regressing against a plain prior (or vice versa) is
    # a config change, not a perf signal.
    ss = int(parsed.get("superstep") or 1)
    cfg_label = "bench" if ss <= 1 else f"bench-ss{ss}"

    def bench_row(n: int, rps, conv=None, conv_wall=None) -> dict:
        return {"kind": "bench", "source": source, "n": int(n),
                "config": cfg_label, "host": host,
                "rounds_per_sec": (round(float(rps), 4)
                                   if rps is not None else None),
                "convergence_rounds": (int(conv)
                                       if conv is not None else None),
                "convergence_wall_s": (round(float(conv_wall), 4)
                                       if conv_wall is not None else None),
                "pallas": pallas, "minute_wall": minute_wall}

    sizes = parsed.get("all_sizes") or {}
    for n_str, rec in sizes.items():
        if not isinstance(rec, dict):
            continue
        rps = rec.get("rounds_per_sec")
        if isinstance(rps, dict):  # live bench.py: {"warm": {...}}
            rps = ((rec.get("warm") or {}).get("rounds_per_sec")
                   or {}).get("median")
            conv = (rec.get("convergence") or {}).get("rounds")
            wall = (rec.get("convergence") or {}).get("wall_s")
        else:
            conv = rec.get("convergence_rounds")
            wall = rec.get("convergence_wall_s")
        if rps is None and isinstance(rec.get("warm"), dict):
            w = rec["warm"].get("rounds_per_sec")
            rps = w.get("median") if isinstance(w, dict) else w
            conv = conv or (rec.get("convergence") or {}).get("rounds")
            wall = wall or (rec.get("convergence") or {}).get("wall_s")
        if rps is not None:
            rows.append(bench_row(int(n_str), rps, conv, wall))

    if not rows and parsed.get("value") is not None:
        # r01/r02 shape: one headline metric, n embedded in the name
        import re

        m = re.search(r"(\d[\d_,]*)-node", str(parsed.get("metric", "")))
        n = int(re.sub(r"[_,]", "", m.group(1))) if m else 0
        unit = str(parsed.get("unit", ""))
        rps = parsed["value"] if "round" in unit else None
        rows.append(bench_row(n, rps))
        if rps is None:
            rows[-1]["metric"] = parsed.get("metric")
            rows[-1]["value"] = parsed.get("value")
            rows[-1]["unit"] = unit
    return rows


def artifact_rows(path: str, **kw) -> list[dict]:
    with open(path) as f:
        doc = json.load(f)
    return doc_rows(doc, os.path.basename(path), **kw)


def _row_key(row: dict) -> tuple:
    if row.get("kind") == "multichip":
        return ("multichip", row.get("source"), row.get("n_devices"))
    # config in the key: one artifact may carry plain AND superstep
    # rows for the same (source, n) — both must land
    return ("bench", row.get("source"), row.get("n"),
            row.get("config", "bench"))


def read_ledger(path: str) -> list[dict]:
    if not os.path.exists(path):
        return []
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    return rows


def append_rows(path: str, rows: list[dict]) -> list[dict]:
    """Append rows not already present (dedup on kind/source/n) —
    append-only: re-ingesting the same artifacts is idempotent.
    Returns the rows actually written."""
    seen = {_row_key(r) for r in read_ledger(path)}
    fresh = [r for r in rows if _row_key(r) not in seen]
    if fresh:
        with open(path, "a") as f:
            for r in fresh:
                f.write(json.dumps(r, sort_keys=True) + "\n")
    return fresh


def ledger_deltas(new_rows: list[dict], prior_rows: list[dict],
                  *, band: float = 0.10) -> list[dict]:
    """Delta each new bench row against the best prior COMPARABLE row:
    same kind/config/n AND same host fingerprint (cross-host
    comparison is refused — reported as no-comparable, never a
    regression), from a different source artifact."""
    out = []
    for row in new_rows:
        if row.get("kind") != "bench" or row.get("rounds_per_sec") is None:
            continue
        cands = [p for p in prior_rows
                 if p.get("kind") == "bench"
                 and p.get("config") == row.get("config")
                 and p.get("n") == row.get("n")
                 and p.get("host") == row.get("host")
                 and p.get("source") != row.get("source")
                 and p.get("rounds_per_sec") is not None]
        d = {"kind": "delta", "source": row.get("source"),
             "n": row.get("n"), "host": row.get("host"),
             "rounds_per_sec": row.get("rounds_per_sec")}
        if not cands:
            cross = any(p.get("kind") == "bench"
                        and p.get("n") == row.get("n")
                        and p.get("host") != row.get("host")
                        for p in prior_rows)
            d.update(delta_pct=None, regression=False,
                     reason=("host-fingerprint mismatch — not comparable"
                             if cross else "no prior comparable entry"))
        else:
            best = max(cands, key=lambda p: p["rounds_per_sec"])
            pct = ((row["rounds_per_sec"] - best["rounds_per_sec"])
                   / best["rounds_per_sec"] * 100.0)
            d.update(best_prior=best["rounds_per_sec"],
                     best_source=best.get("source"),
                     delta_pct=round(pct, 2),
                     regression=bool(pct < -band * 100.0))
        out.append(d)
    return out


# --------------------------------------------------------------------
# synthetic capture (test fixture) — encodes a REAL capture layout so
# tests exercise the exact parse path live captures take
# --------------------------------------------------------------------


def write_synthetic_capture(trace_dir: str, module: str,
                            ops: list[tuple[str, str, float]]) -> None:
    """Write a ``plugins/profile/<run>/host.{xplane.pb,trace.json.gz}``
    pair for ``ops`` = [(op_name, scope_path, dur_us), ...]."""
    run = os.path.join(trace_dir, "plugins", "profile", "0001")
    os.makedirs(run, exist_ok=True)

    insts = b"".join(
        _encode_field(2, _encode_field(1, op) +
                      _encode_field(7, _encode_field(2, scope)))
        for op, scope, _ in ops)
    hlo_module = _encode_field(1, f"{module}(1)") + _encode_field(3, insts)
    hlo_proto = _encode_field(1, hlo_module)
    # stat_metadata map: id 61 -> "Hlo Proto"; one stat carrying it
    stat_md = _encode_field(
        5, _encode_field(1, 61) +
        _encode_field(2, _encode_field(1, 61) +
                      _encode_field(2, "Hlo Proto")))
    stat = _encode_field(1, 61) + _encode_field(6, hlo_proto)
    # the real jax 0.4.x layout: HloProto stat attached to the
    # module's XEventMetadata entry in the event_metadata map
    event_md = _encode_field(
        4, _encode_field(1, 7) +
        _encode_field(2, _encode_field(1, 7) +
                      _encode_field(2, f"{module}(1)") +
                      _encode_field(5, stat)))
    plane = _encode_field(1, _encode_field(2, "/host:metadata") +
                          stat_md + event_md)
    with open(os.path.join(run, "host.xplane.pb"), "wb") as f:
        f.write(plane)

    events = [{"ph": "X", "ts": 1000 + i, "dur": dur, "name": op,
               "pid": 1, "tid": 1,
               "args": {"hlo_module": module, "hlo_op": op}}
              for i, (op, _scope, dur) in enumerate(ops)]
    with gzip.open(os.path.join(run, "host.trace.json.gz"), "wt") as f:
        json.dump({"traceEvents": events}, f)
