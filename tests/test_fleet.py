"""Fleet runner (partisan_tpu/fleet.py): vmapped cluster populations.

The load-bearing contract is FLEET-VS-LOOP BIT-PARITY: member j of a
vmapped fleet evolves bit-identically to an unbatched serial run with
the same salt — through calm rounds, per-member crash+partition
storms, flash-crowd traffic, the chunked soak engine and
checkpoint/resume.  On top of it: the salted counter-hash contract
(salt=0 == the unsalted program; salt=s == a native seed+s run), the
batched Filibuster search's one-program + counterexample-replay
acceptance (ISSUE 14), and the band-population tuner reproducing the
committed CONTROL_AB fanout verdict.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from partisan_tpu import fleet as fleet_mod
from partisan_tpu import interpose, soak, workload
from partisan_tpu.cluster import Cluster, with_salt
from partisan_tpu.config import Config, PlumtreeConfig, TrafficConfig
from partisan_tpu.models.plumtree import Plumtree
from tests.support import (FLEET_PAR_W, FLEET_SEARCH_W, FLEET_TUNE_N,
                           FLEET_TUNE_WAVES, assert_states_bitidentical)


def _cfg(n=24, seed=7, **kw):
    kw.setdefault("msg_words", 16)
    kw.setdefault("partition_mode", "groups")
    kw.setdefault("salt_operand", True)
    return Config(n_nodes=n, seed=seed, peer_service_manager="hyparview",
                  **kw)


def _joined(cl_or_fl, st, cfg):
    joins, contacts = list(range(1, cfg.n_nodes)), [0] * (cfg.n_nodes - 1)
    if isinstance(cl_or_fl, fleet_mod.Fleet):
        return st._replace(manager=cl_or_fl.map_members(
            lambda m: cl_or_fl.manager.join_many(cfg, m, joins, contacts),
            st.manager))
    return st._replace(manager=cl_or_fl.manager.join_many(
        cfg, st.manager, joins, contacts))


def _no_salt(state):
    """Drop the salt leaf for comparison against salt_operand=False
    states (the only structural difference the flag introduces)."""
    return state._replace(salt=())


# ---------------------------------------------------------------------------
# Satellite: salted counter-hash characterization
# ---------------------------------------------------------------------------

def test_salt_streams_diverge_and_salt0_is_bitidentical():
    """The per-cluster salt namespaces every in-scan stream: a W=2
    fleet with salts (0, 5) has member 0 bit-identical to the plain
    UNSALTED unbatched run (salt_operand=False — the pre-fleet
    program), member 1 bit-identical to a native Config(seed=base+5)
    run, and the two members' trajectories diverge."""
    n, seed, k = 24, 7, 12
    cfg = _cfg(n, seed)

    def drive(cl, st):
        st = _joined(cl, st, cfg)
        if isinstance(cl, fleet_mod.Fleet):
            # batched leaves take batched writes (the Member-wrapper
            # rule — a scalar write would deflate the fleet axis)
            st = st._replace(faults=st.faults._replace(
                link_drop=jnp.full((cl.width,), 0.1, jnp.float32)))
            st = st._replace(model=cl.map_members(
                lambda m: cl.model.broadcast(m, 0, 0, 3), st.model))
        else:
            st = st._replace(faults=st.faults._replace(
                link_drop=jnp.float32(0.1)))
            st = st._replace(model=cl.model.broadcast(st.model, 0, 0, 3))
        return cl.steps(st, k)

    fl = fleet_mod.Fleet(cfg, width=2, model=Plumtree())
    fst = drive(fl, fl.init(salts=np.asarray([0, 5], np.uint32)))

    plain = Cluster(cfg.replace(salt_operand=False, fleet_width=0),
                    model=Plumtree())
    p = drive(plain, plain.init())
    assert_states_bitidentical(
        p, _no_salt(fl.member_state(fst, 0)), "salt0-vs-unsalted")

    native = Cluster(cfg.replace(seed=seed + 5, salt_operand=False,
                                 fleet_width=0), model=Plumtree())
    nst = drive(native, native.init())
    assert_states_bitidentical(
        nst, _no_salt(fl.member_state(fst, 1)), "salt5-vs-native")

    m0, m1 = fl.member_state(fst, 0), fl.member_state(fst, 1)
    diff = sum(
        int(not np.array_equal(np.asarray(jax.device_get(a)),
                               np.asarray(jax.device_get(b))))
        for a, b in zip(jax.tree.leaves(m0), jax.tree.leaves(m1)))
    assert diff > 0, "members with different salts did not diverge"


def test_traced_seed_hash_paths_match_static():
    """edge_hash / rank32 with a traced uint32 seed reproduce the
    Python-int path bit-for-bit (the uint32-wraparound == mod-2**32
    identity every salted stream relies on)."""
    from partisan_tpu import faults
    from partisan_tpu.ops import rng

    rnd = jnp.int32(13)
    src = jnp.arange(6, dtype=jnp.int32)
    dst = src[::-1]
    for seed in (0, 7, 2**31 + 9):
        h_static = faults.edge_hash(seed, rnd, 11, src, dst)
        h_traced = jax.jit(lambda s: faults.edge_hash(
            s, rnd, 11, src, dst))(jnp.uint32(seed))
        np.testing.assert_array_equal(np.asarray(h_static),
                                      np.asarray(h_traced))
        r_static = rng.rank32(seed, rnd, 31, src, dst)
        r_traced = jax.jit(lambda s: rng.rank32(
            s, rnd, 31, src, dst))(jnp.uint32(seed))
        np.testing.assert_array_equal(np.asarray(r_static),
                                      np.asarray(r_traced))
        k_static = rng.node_keys(seed, rnd, src)
        k_traced = jax.jit(lambda s: rng.node_keys(s, rnd, src))(
            jnp.uint32(seed))
        np.testing.assert_array_equal(
            np.asarray(jax.random.key_data(k_static)),
            np.asarray(jax.random.key_data(k_traced)))


# ---------------------------------------------------------------------------
# Fleet-vs-loop parity: storms + traffic + soak engine + checkpoints
# ---------------------------------------------------------------------------

def _storm_cfg(n=24, seed=9):
    # dense partition mode: the Partition member-storm below needs the
    # per-(src,dst) matrix; traffic on for the flash-crowd member
    return _cfg(n, seed, partition_mode="dense",
                traffic=TrafficConfig(enabled=True, rate_x1000=300,
                                      ring=16))


def _member_events(n):
    """Per-member storm timelines: member 1 rides a crash+partition
    storm, member 2 a flash-crowd traffic ramp; the rest stay calm."""
    crash = soak.CrashBatch(nodes=(3, 5))
    part = soak.Partition(at=n // 2)
    heal = soak.Heal()
    crowd = workload.flash_crowd(10, 10, 3000, 300)
    member1 = ((8, crash), (12, part), (24, heal))
    member2 = tuple(crowd)
    return member1, member2


def test_fleet_vs_serial_bitparity_under_member_storms():
    """A W-member fleet driven through the chunked soak engine with
    per-member storm timelines (Member-wrapped crash+partition on one
    member, a flash-crowd traffic ramp on another) is bit-identical,
    member by member, to W serial soak runs with the bare actions —
    the fleet-vs-loop contract under exactly the fault surfaces the
    sweep drivers script."""
    n, seed, horizon, W = 24, 9, 36, FLEET_PAR_W
    cfg = _storm_cfg(n, seed)
    member1, member2 = _member_events(n)

    fl = fleet_mod.Fleet(cfg, width=W, model=Plumtree())
    fst = _joined(fl, fl.init(), cfg)
    events = tuple((off, fleet_mod.Member(1, act)) for off, act in member1)
    events += tuple((off, fleet_mod.Member(2, act)) for off, act in member2)
    storm = soak.Storm(events=tuple(sorted(events, key=lambda e: e[0])))
    engine = soak.Soak(make_cluster=lambda: fl, storm=storm,
                       invariants=[soak.conservation()],
                       cfg=soak.SoakConfig(chunk_fixed=6))
    res = engine.run(fst, rounds=horizon)
    assert res.breaches == 0
    final = res.state

    # serial twins: one calm member plus BOTH storm members (further
    # calm members are redundant with member 0 — each serial run
    # compiles its own programs, the suite's cost driver)
    for j in range(min(W, 3)):
        per = {1: member1, 2: member2}.get(j, ())
        cl = Cluster(cfg.replace(fleet_width=0), model=Plumtree())
        st = with_salt(_joined(cl, cl.init(), cfg), j)
        sstorm = soak.Storm(events=per) if per else None
        st = soak.reference_run(cl, st, horizon, storm=sstorm)
        assert_states_bitidentical(st, fl.member_state(final, j),
                                   f"member{j}")


def test_fleet_checkpoint_resume_roundtrip(tmp_path):
    """A fleet soak checkpoint/resume roundtrip through the soak
    engine: kill after the first leg, resume from disk in a FRESH
    engine, and land bit-identical to the uninterrupted run.  The
    fingerprint carries Config.fleet_width, so a fleet snapshot
    refuses to restore against the member (unbatched) config."""
    from partisan_tpu import checkpoint

    n, seed, W = 24, 11, 2
    cfg = _storm_cfg(n, seed)
    crash, part = soak.CrashBatch(nodes=(3, 5)), soak.Partition(at=n // 2)
    storm = soak.Storm(events=(
        (6, fleet_mod.Member(1, crash)), (12, fleet_mod.Member(1, part)),
        (18, fleet_mod.Member(1, soak.Heal()))))
    warm = fleet_mod.Fleet(cfg, width=W, model=Plumtree())

    def run_leg(fl, rounds, state=None, resume=False):
        engine = soak.Soak(
            make_cluster=lambda: fl, storm=storm,
            cfg=soak.SoakConfig(chunk_fixed=6,
                                checkpoint_dir=str(tmp_path)))
        if state is None and not resume:
            state = _joined(fl, fl.init(), cfg)
        return engine.run(state, rounds=rounds, resume=resume).state

    run_leg(warm, 12)
    # fresh-process leg: a NEW Fleet (fresh jitted programs) resumes
    # from disk and continues
    st2 = run_leg(fleet_mod.Fleet(cfg, width=W, model=Plumtree()),
                  12, resume=True)
    # uninterrupted reference reuses the warm fleet's programs
    full = run_leg(warm, 24)
    assert_states_bitidentical(st2, full, "resume-vs-uninterrupted")

    # fingerprint: fleet checkpoints are not member checkpoints
    steps = checkpoint.steps(str(tmp_path))
    assert steps, "no checkpoints written"
    member_cl = Cluster(cfg.replace(fleet_width=0), model=Plumtree())
    with pytest.raises(checkpoint.CheckpointError):
        checkpoint.restore(
            tmp_path / f"ckpt_{steps[-1]}.npz", member_cl.init(),
            cfg=member_cl.cfg)


def test_raw_action_on_fleet_state_needs_member_wrapper():
    """Member() validates its target; and the wrapper refuses plain
    clusters — the guard rails around 'never apply a raw action to a
    batched state'."""
    cfg = _cfg(16)
    fl = fleet_mod.Fleet(cfg, width=2, model=Plumtree())
    st = fl.init()
    with pytest.raises(ValueError):
        fleet_mod.Member(5, soak.Heal()).apply(fl, st, 0)
    cl = Cluster(cfg.replace(fleet_width=0), model=Plumtree())
    with pytest.raises(ValueError):
        fleet_mod.Member(0, soak.Heal()).apply(cl, cl.init(), 0)


# ---------------------------------------------------------------------------
# Satellite: stacked schedule batches + frame convention
# ---------------------------------------------------------------------------

def test_schedule_drops_batch_stacks_and_validates():
    from partisan_tpu import filibuster

    s0 = frozenset()
    s1 = frozenset({(2, 1, 3), (4, 0, 0)})
    single = filibuster.schedule_drops(s1, 6, 4, 5)
    batch = filibuster.schedule_drops([s0, s1], 6, 4, 5)
    assert batch.shape == (2, 6, 4, 5)
    assert not batch[0].any()
    np.testing.assert_array_equal(batch[1], single)
    with pytest.raises(ValueError):
        filibuster.schedule_drops([frozenset({(9, 0, 0)})], 6, 4, 5)
    with pytest.raises(ValueError):
        filibuster.schedule_drops([frozenset({(0, 0, 7)})], 6, 4, 5)


def test_omission_schedule_rejects_misranked_drops():
    """A mis-ranked drops tensor (missing round axis, or an already
    stacked batch) must fail loudly at init — apply() would otherwise
    silently index senders as rounds."""
    cfg = _cfg(8, salt_operand=False)
    cl = Cluster(cfg)
    for bad in (np.zeros((8, 4), bool), np.zeros((2, 6, 8, 4), bool)):
        with pytest.raises(ValueError):
            interpose.OmissionSchedule(bad).init(cfg, cl.comm)


def test_short_schedule_tail_passes_through():
    """The frame convention's tail rule: a schedule shorter than the
    horizon omits nothing past its window (never broadcasts its last
    row) — the blackout rows suppress every delivery, the rounds after
    the window deliver again."""
    n = 16
    cfg = _cfg(n, seed=5, salt_operand=False)
    T = 6
    drops = np.ones((T, n, 64), bool)        # blackout rounds 0..5 only
    cl = Cluster(cfg, model=Plumtree(),
                 interpose=interpose.OmissionSchedule(drops, start=0))
    st = _joined(cl, cl.init(), cfg)
    st = cl.steps(st, T)
    s = jax.device_get(st.stats)
    assert int(s.emitted) == 0               # in-window: everything cut
    st = cl.steps(st, 10)
    s = jax.device_get(st.stats)
    assert int(s.emitted) > 0                # past the window: pass-through


# ---------------------------------------------------------------------------
# The acceptance drivers: batched search + band tuning
# ---------------------------------------------------------------------------

def test_fleet_search_w64_one_program_and_counterexample_replay():
    """ISSUE 14 acceptance: a W>=64 fleet.search over distinct fault
    schedules runs as ONE jitted program per scan length (the jit
    cache guard — no per-member retrace), every failing schedule's
    counterexample replays bit-identically through the unbatched path
    (search raises if not; we also re-assert coverage here), and the
    passing schedules pass."""
    n, W, horizon, settle = 16, FLEET_SEARCH_W, 10, 30
    cfg = _cfg(n, seed=5, plumtree=PlumtreeConfig(aae=False))
    joins, contacts = list(range(1, n)), [0] * (n - 1)

    def build(sched):
        fl = fleet_mod.Fleet(cfg, width=W, model=Plumtree(),
                             interpose=sched)
        st = fl.init(salts=np.zeros(W, np.uint32))
        st = st._replace(manager=fl.map_members(
            lambda m: fl.manager.join_many(cfg, m, joins, contacts),
            st.manager))
        st = fl.steps(st, settle)
        st = st._replace(model=fl.map_members(
            lambda m: fl.model.broadcast(m, 0, 0, 3), st.model))
        return fl, st

    # golden trace -> candidate population (serial member twin)
    cl = Cluster(cfg.replace(fleet_width=0), model=Plumtree(),
                 interpose=interpose.OmissionSchedule(
                     np.zeros((1, 1, 1), np.bool_), start=0))
    st = _joined(cl, cl.init(), cfg)
    st = cl.steps(st, settle)
    st = st._replace(model=cl.model.broadcast(st.model, 0, 0, 3))
    from partisan_tpu import trace as trace_mod

    _, capture = cl.record(st, horizon)
    emit_w = capture.sent.shape[2]
    tr = trace_mod.from_capture(capture)
    boot = int(jax.device_get(st.rnd))
    scheds = fleet_mod.population(
        tr, lambda e: e.kind_name.startswith("PT_"),
        width=W - 1, max_faults=2, seed=1)
    # one adversarial member: silence the broadcast root for the whole
    # horizon — with AAE off, dissemination is wire-only, so coverage
    # MUST fail (the deterministic counterexample)
    scheds.append(frozenset(
        (r, 0, e) for r in range(boot, boot + horizon)
        for e in range(emit_w)))
    assert len(set(scheds)) == W, "schedules must be distinct"

    res = fleet_mod.search(build, scheds, horizon, sched_width=emit_w,
                           coverage_slot=0, coverage_version=3)
    assert not res.passed
    assert res.verdicts[:-1].count(False) == 0, \
        "trace-guided small schedules should be tolerated here"
    assert res.verdicts[-1] is False
    [cex] = res.counterexamples
    assert cex.member == W - 1 and cex.replayed
    assert cex.seed == cfg.seed        # salt 0: same-environment search
    assert cex.oracle["coverage_value"] == pytest.approx(1 / n)
    # the jit-cache guard: TWO scan lengths total (settle + horizon),
    # W-INDEPENDENT — a per-member retrace would show up here
    assert res.programs == 2, res.programs


def test_fleet_tune_reproduces_control_ab_fanout_winner():
    """ISSUE 14 acceptance: population-based band tuning reproduces
    the committed CONTROL_AB.json fanout verdict from a band
    population containing the winner — the default (adaptive) bands
    beat a static-equivalent setting (hi band unreachable => the
    governor never demotes and the eager cap pins at the overlay
    width) on steady-state redundancy at full coverage."""
    bands = [{"fanout_hi_pct": 200}, {}]        # [static-like, winner]
    out = fleet_mod.tune(bands, n=FLEET_TUNE_N, waves=FLEET_TUNE_WAVES)
    assert out["winner"] == 1, out
    assert out["winner_bands"] == {}
    m_static, m_adapt = out["members"]
    assert m_static["coverage"] == 1.0 and m_adapt["coverage"] == 1.0
    assert (m_adapt["steady_redundancy_ratio"]
            < m_static["steady_redundancy_ratio"]), out
    # band population ran as one program per scan length, not one per
    # member (settle + wave + drain lengths)
    assert out["programs"] <= 3


def test_set_bands_maps_and_validates():
    from partisan_tpu.config import ControlConfig

    cfg = _cfg(16, provenance=True, provenance_ring=16,
               control=ControlConfig(fanout=True, ring=8))
    fl = fleet_mod.Fleet(cfg, width=3, model=Plumtree())
    st = fl.init()
    st = fleet_mod.set_bands(st, [{"fanout_hi_pct": 55},
                                  {"fanout_min": 3},
                                  {}])
    fan = jax.device_get(st.control.fanout)
    np.testing.assert_array_equal(np.asarray(fan.band_hi), [55, 40, 40])
    np.testing.assert_array_equal(np.asarray(fan.band_min), [2, 3, 2])
    with pytest.raises(ValueError):
        fleet_mod.set_bands(st, [{"bogus": 1}, {}, {}])
    with pytest.raises(ValueError):
        fleet_mod.set_bands(st._replace(control=()), [{}, {}, {}])


# ---------------------------------------------------------------------------
# Sweep card
# ---------------------------------------------------------------------------

def test_fleet_sweep_card_distributions():
    """scenarios.fleet_sweep: every member converges, the card carries
    distributions over the population, and the run stays a handful of
    programs (width-independent).  Kept tiny — the tools CLI smoke
    (tests/test_tools_cli.py::test_fleet_report_cli_smoke) runs the
    full exporter at 3x32 end-to-end."""
    from partisan_tpu import scenarios

    card = scenarios.fleet_sweep(width=2, n=24, max_rounds=120,
                                 settle=24)
    assert card["converged"] == 2
    d = card["rounds_to_converge"]
    assert d["count"] == 2 and d["missing"] == 0
    assert 0 <= d["p5"] <= d["p50"] <= d["p95"]
    assert card["programs"] <= 2
    assert set(card["members"]["rounds_to_converge"]) != {-1}
