"""16-node bridge-path trace validation (the north-star's live-trace
substitute — see partisan_tpu/bridge/trace16.py).

The committed artifact ``tools/traces/trace16.json`` is a full
wire-format capture of the 16-node anti-entropy scenario executed
END-TO-END over the multi-VM TCP transport.  This suite:

1. re-runs the harness and requires the SAME trace (host RNG seeded,
   simulator deterministic — any divergence is a transport or manager
   regression),
2. validates trace causality: every delivery row has a matching send
   row in the same round, and the rumor's first-reach round per node is
   monotone along the infection chain,
3. validates convergence against the in-simulator AntiEntropy model at
   the same size (both spread one rumor to 16 nodes within the demers
   bound; the bridge path runs the protocol at the app level, so the
   round counts are same-order, not identical).
"""

import json
from pathlib import Path

import pytest

from partisan_tpu.bridge.trace16 import (
    MAX_ROUNDS, N, ORIGIN, RUMOR, run_trace16, sim_convergence_rounds)

ARTIFACT = Path(__file__).parent.parent / "tools" / "traces" / "trace16.json"


@pytest.fixture(scope="module")
def fresh():
    return run_trace16()


def test_trace_matches_committed_artifact(fresh):
    """Byte-exact trace equality is gated on the numpy version the
    artifact was captured under: the host RNG's bit-stream (rng.choice)
    is not guaranteed stable across numpy releases, so on a different
    numpy the check degrades to structural equality (scenario shape +
    convergence) instead of breaking without any code change."""
    import numpy as np

    committed = json.loads(ARTIFACT.read_text())
    if committed.get("numpy_version") == np.__version__:
        assert committed["convergence_rounds"] == \
            fresh["convergence_rounds"]
        assert committed["rows"] == fresh["rows"]
    else:
        for key in ("n", "seed", "fanout", "rumor", "origin"):
            assert committed[key] == fresh[key]
        assert 0 < committed["convergence_rounds"] <= MAX_ROUNDS
        assert 0 < fresh["convergence_rounds"] <= MAX_ROUNDS


def test_trace_causality(fresh):
    """Every delivery has a same-round send; nobody emits the rumor
    before holding it."""
    sends = set()
    holds = {ORIGIN: -1}        # node -> round it first held the rumor
    for rnd, src, dst, payload in fresh["rows"]:
        key = (rnd, src, dst, tuple(payload))
        if key in sends:        # second occurrence = the delivery row
            if RUMOR in payload and dst not in holds:
                holds[dst] = rnd
            continue
        sends.add(key)
        if RUMOR in payload:
            assert src in holds and holds[src] < rnd or src == ORIGIN, \
                f"node {src} sent the rumor in round {rnd} before holding it"
    assert set(holds) == set(range(N))


def test_bridge_convergence_within_demers_bound(fresh):
    conv = fresh["convergence_rounds"]
    assert 0 < conv <= MAX_ROUNDS
    # anti-entropy with fanout 2 on 16 nodes: log-ish spread
    assert conv <= 10, f"bridge-path convergence suspiciously slow: {conv}"


def test_sim_convergence_same_order(fresh):
    sim = sim_convergence_rounds()
    assert sim > 0
    # app-level push (bridge) vs model push-pull (sim): same order of
    # magnitude, both within the demers bound for n=16
    assert abs(sim - fresh["convergence_rounds"]) <= 8, (sim, fresh)
