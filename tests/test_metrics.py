"""Metrics-plane suite (metrics.py + cluster.round_body accumulation):

- ring wraparound keeps the most recent window,
- per-round cause-tagged drop sums reconcile EXACTLY with the legacy
  cumulative ``Stats`` counters (the acceptance invariant),
- sharded runs record cluster-wide series bit-identical to
  single-device runs,
- the disabled flag keeps the ClusterState leaf an empty pytree,
- the ring is a scan CARRY: no host callback inside the jitted scan.
"""

import jax
import jax.numpy as jnp
import numpy as np

from partisan_tpu import metrics as metrics_mod
from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config, PlumtreeConfig
from tests import support


def _faulted_hyparview_run(n=64, rounds=100, ring=256):
    """HyParView + plumtree broadcast with crashes + iid link drop and a
    deliberately tight inbox, so every hot drop cause fires."""
    from partisan_tpu.models.plumtree import Plumtree

    cfg = Config(n_nodes=n, seed=5, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups",
                 max_broadcasts=4, inbox_cap=8,
                 metrics=True, metrics_ring=ring,
                 plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4))
    cl = Cluster(cfg, model=Plumtree())
    st = cl.init()
    m = cl.manager.join_many(cfg, st.manager, list(range(1, n)),
                             [0] * (n - 1))
    st = cl.steps(st._replace(manager=m), 20)
    st = st._replace(model=cl.model.broadcast(st.model, 0, 0, 7))
    alive = st.faults.alive.at[jnp.asarray([5, 17])].set(False)
    st = st._replace(faults=st.faults._replace(
        alive=alive, link_drop=jnp.float32(0.1)))
    st = cl.steps(st, rounds - 20)
    return cfg, cl, st


def test_disabled_flag_zero_overhead_pytree():
    """metrics=False (the default) must keep the state leaf an empty ()
    — no arrays, no ring, identical treedef to the pre-metrics state."""
    cl = Cluster(Config(n_nodes=16, seed=1))
    st = cl.init()
    assert st.metrics == ()
    assert len(jax.tree.leaves(st.metrics)) == 0
    st2 = cl.steps(st, 5)
    assert st2.metrics == ()


def test_ring_wraparound_keeps_latest_window():
    cfg = Config(n_nodes=16, seed=1, metrics=True, metrics_ring=8)
    cl = Cluster(cfg)
    st = cl.init()
    m = st.manager
    for i in range(1, 16):
        m = cl.manager.join(cfg, m, i, 0)
    st = cl.steps(st._replace(manager=m), 20)
    snap = metrics_mod.snapshot(st.metrics)
    # 20 rounds through an 8-slot ring: the last 8 rounds, in order.
    assert snap["rounds"].tolist() == list(range(12, 20))
    # a shorter-than-ring run reports only what ran
    st2 = cl.steps(cl.init()._replace(manager=m), 3)
    assert metrics_mod.snapshot(st2.metrics)["rounds"].tolist() == [0, 1, 2]


def test_cause_sum_reconciles_with_legacy_stats():
    """The acceptance invariant: a 100-round faulted run's per-round,
    per-channel counters sum EXACTLY to the legacy cumulative Stats —
    emissions per channel, deliveries per channel (+ causal), and the
    cause-tagged drops."""
    _, _, st = _faulted_hyparview_run(rounds=100, ring=256)
    snap = metrics_mod.snapshot(st.metrics)
    tot = metrics_mod.totals(snap)
    assert tot["rounds"] == 100
    assert tot["emitted"] == int(st.stats.emitted)
    assert tot["delivered"] == int(st.stats.delivered)
    assert tot["dropped"] == int(st.stats.dropped)
    # the scenario actually exercised the causes
    assert tot["drops_by_cause"]["fault_cut"] > 0
    assert tot["drops_by_cause"]["inbox_overflow"] > 0
    # nothing leaked into the residual on this path (no a2a quota, no
    # channel capacity): the direct counters fully explain the delta
    assert tot["drops_by_cause"]["other"] == 0
    # per-round reconciliation, not only in aggregate: each round's
    # cause sum equals that round's emitted-minus-delivered delta
    per_round = snap["emitted"].sum(axis=1) - snap["delivered"].sum(axis=1)
    assert (snap["drops"].sum(axis=1) == per_round).all()
    # occupancy/hwm are consistent and bounded by the inbox capacity
    assert (snap["inbox_hwm"] <= 8).all()
    assert (snap["inbox_occ"] >= snap["inbox_hwm"]).all()
    # liveness series reflects the two crashes
    assert snap["alive"][-1] == 62


def test_sharded_series_match_single_device():
    """Cluster-wide metrics series must be placement-invariant: the same
    run on 1 device and on a mesh records bit-identical rings (every
    recorded value is allsum/allmax-reduced before the write)."""
    import pytest

    if not hasattr(jax, "shard_map"):
        pytest.skip("jax.shard_map unavailable on this jax "
                    "(parallel/sharded.py requires it)")
    from partisan_tpu.models.anti_entropy import AntiEntropy
    from partisan_tpu.parallel.sharded import ShardedCluster, make_mesh

    cfg = Config(n_nodes=16, seed=3, metrics=True, metrics_ring=64,
                 inbox_cap=24)

    def drive(cl):
        st = cl.init()
        m = st.manager
        for i in range(1, 16):
            m = cl.manager.join(cfg, m, i, 0)
        st = cl.steps(st._replace(manager=m), 10)
        st = st._replace(model=cl.model.broadcast(st.model, 0, 0))
        alive = st.faults.alive.at[7].set(False)
        st = st._replace(faults=st.faults._replace(
            alive=alive, link_drop=jnp.float32(0.2)))
        return cl.steps(st, 30)

    st_l = drive(Cluster(cfg, model=AntiEntropy()))
    st_s = drive(ShardedCluster(cfg, make_mesh(), model=AntiEntropy()))
    snap_l = metrics_mod.snapshot(st_l.metrics)
    snap_s = metrics_mod.snapshot(st_s.metrics)
    for name, series in snap_l.items():
        assert np.array_equal(series, snap_s[name]), name
    # and the series carried real traffic + drops
    assert metrics_mod.totals(snap_l)["emitted"] > 0
    assert metrics_mod.totals(snap_l)["dropped"] > 0


def test_cause_taxonomy_stays_in_sync():
    """Guard: a new drop cause must update N_CAUSES, CAUSE_NAMES, the
    rows() decoder, AND the latency plane's drop-age axis together — a
    silent mismatch misaligns every exported column."""
    from partisan_tpu import latency as latency_mod

    assert len(metrics_mod.CAUSE_NAMES) == metrics_mod.N_CAUSES
    # the CAUSE_* indices cover exactly [0, N_CAUSES)
    idx = sorted(getattr(metrics_mod, k) for k in dir(metrics_mod)
                 if k.startswith("CAUSE_") and k != "CAUSE_NAMES")
    assert idx == list(range(metrics_mod.N_CAUSES))
    # rows() labels the drops axis with the taxonomy, in order
    cfg = Config(n_nodes=8, seed=1, metrics=True, metrics_ring=8)
    cl = Cluster(cfg)
    st = cl.steps(cl.init(), 3)
    snap = metrics_mod.snapshot(st.metrics)
    row = metrics_mod.rows(snap)[0]
    assert tuple(row["drops"].keys()) == metrics_mod.CAUSE_NAMES
    assert tuple(metrics_mod.totals(snap)["drops_by_cause"].keys()) \
        == metrics_mod.CAUSE_NAMES
    # the device-side drops vector and the latency drop-age axis are
    # sized by the same constant
    assert snap["drops"].shape[1] == metrics_mod.N_CAUSES
    assert latency_mod.init(cfg).drop_age.shape[0] == metrics_mod.N_CAUSES


def test_metrics_state_is_scan_carry_no_callbacks():
    """The acceptance criterion's 'no host transfer inside the scan':
    the metrics ring rides the lax.scan carry — the jitted k-round
    program is clean under the shared lint rules (no host-callback
    primitives anywhere in the program, every OFF plane traceless)."""
    cfg = Config(n_nodes=16, seed=1, metrics=True, metrics_ring=16)
    cl = Cluster(cfg)
    st = cl.init()
    support.assert_scan_lint_clean(cl, st, 8)
    # the ring leaves really are carried: they appear in the scan output
    out = cl.steps(st, 8)
    assert metrics_mod.snapshot(out.metrics)["rounds"].tolist() \
        == list(range(8))
