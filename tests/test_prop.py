"""Property-harness tests (reference test/prop_partisan.erl + the crash
fault model prop_partisan_crash_fault_model.erl): schedulers, fault
budget, postcondition detection, and shrinking."""

import random

import pytest

from partisan_tpu.prop import Command, CrashFaultModel, Harness
from partisan_tpu.prop_models import (NoopSystem, PrimaryBackupSystem,
                                      ReliableBroadcastSystem)


def test_noop_system_passes():
    res = Harness(system=NoopSystem(seed=2), n_runs=2, n_commands=3).run()
    assert res.ok
    assert "PASSED" in res.render()


def test_reliable_broadcast_acked_survives_omissions():
    sys = ReliableBroadcastSystem(seed=7, acked=True)
    res = Harness(
        system=sys,
        fault_model=CrashFaultModel(tolerance=2, allow_crash=False),
        scheduler="finite_fault", n_runs=3, n_commands=6, seed=101).run()
    assert res.ok, res.render()


def test_reliable_broadcast_unacked_fails_under_omission_and_shrinks():
    # Deterministic canary: an explicit script (broadcast from node 2
    # while edge 2->4 is cut) must violate reliable broadcast for the
    # unacked protocol, and shrinking must keep it minimal.
    sys = ReliableBroadcastSystem(seed=7, acked=False)
    h = Harness(system=sys,
                fault_model=CrashFaultModel(tolerance=1, allow_crash=False),
                scheduler="finite_fault", n_runs=1, n_commands=4, seed=0)
    rng = random.Random(0)
    omit = CrashFaultModel(allow_crash=False).gen_fault.__wrapped__ \
        if hasattr(CrashFaultModel.gen_fault, "__wrapped__") else None
    del omit, rng
    cl, st = sys.build()
    from partisan_tpu import faults as faults_mod
    script = [
        Command(name="omit_edge", args=(2, 4), kind="fault",
                apply=lambda c, s: s._replace(
                    faults=faults_mod.inject_partition(s.faults, [2], [4]))),
        Command(name="broadcast", args=(2, 0),
                apply=lambda c, s: s._replace(
                    model=sys.model.broadcast(s.model, 2, 0))),
    ]
    assert not h._execute(script)
    shrunk = h._shrink(script)
    assert len(shrunk) == 2  # both commands are required for the failure
    # Healing before settle lets the ACKED variant pass the same script.
    sys2 = ReliableBroadcastSystem(seed=7, acked=True)
    h2 = Harness(system=sys2, n_runs=1)
    script2 = [
        Command(name="omit_edge", args=(2, 4), kind="fault",
                apply=lambda c, s: s._replace(
                    faults=faults_mod.inject_partition(s.faults, [2], [4]))),
        Command(name="broadcast", args=(2, 0),
                apply=lambda c, s: s._replace(
                    model=sys2.model.broadcast(s.model, 2, 0))),
    ]
    assert h2._execute(script2)


def test_primary_backup_acked_passes_default_scheduler():
    sys = PrimaryBackupSystem(seed=5, acked=True)
    res = Harness(system=sys, scheduler="default", n_runs=2,
                  n_commands=5, seed=40).run()
    assert res.ok, res.render()


def test_primary_backup_crash_aware_postcondition():
    # Crash a client right after its write: the postcondition must NOT
    # flag the run (crashed clients are unconstrained).
    sys = PrimaryBackupSystem(seed=6, acked=True)
    from partisan_tpu import faults as faults_mod
    h = Harness(system=sys, n_runs=1)
    script = [
        Command(name="write", args=(2, 0, 111),
                apply=lambda c, s: s._replace(
                    model=sys.model.write(s.model, 2, 0, 111))),
        Command(name="crash", args=(2,), kind="fault",
                apply=lambda c, s: s._replace(
                    faults=faults_mod.crash(s.faults, 2))),
    ]
    assert h._execute(script)


def test_fault_model_budget_and_guards():
    fm = CrashFaultModel(tolerance=1, allow_crash=True, allow_omission=False,
                         protect=frozenset(range(4)))
    sys = NoopSystem(n_nodes=4, seed=2)
    cl, st = sys.build()
    with pytest.raises(ValueError):
        fm.gen_fault(random.Random(0), cl, st)
    # With a victim available it produces a crash command.
    fm2 = CrashFaultModel(allow_omission=False, protect=frozenset({0}))
    cmd = fm2.gen_fault(random.Random(0), cl, st)
    assert cmd.name == "crash" and cmd.args[0] != 0


def test_single_success_scheduler_stops_after_one_run():
    sys = NoopSystem(seed=3)
    res = Harness(system=sys, scheduler="single_success", n_runs=10,
                  n_commands=2).run()
    assert res.ok and res.seed == 0 + 0  # stopped at the first seed


def test_linearizability_system_passes_and_detects():
    from partisan_tpu.prop_models import LinearizabilitySystem

    sys = LinearizabilitySystem(seed=8)
    res = Harness(system=sys, scheduler="default", n_runs=2,
                  n_commands=4, seed=77).run()
    assert res.ok, res.render()
    # Detection: a final state whose register holds a non-last value
    # must fail the property (simulate by checking the postcondition
    # against a doctored script order).
    cl, st = sys.build()
    s1 = sys.gen_command(__import__("random").Random(1), cl, st)
    s2 = sys.gen_command(__import__("random").Random(2), cl, st)
    st = s1.apply(cl, st)
    st = cl.steps(st, 15)
    st = s2.apply(cl, st)
    st = cl.steps(st, 15)
    assert sys.postcondition(cl, st, [s1, s2])
    assert not sys.postcondition(cl, st, [s2, s1]), \
        "reordered history must violate linearizability"


def test_atomic_commit_app_under_test_2pc_blocks_ctp_repairs():
    """The application-under-test model (prop_partisan_hbbft role): the
    commit engine hosted in the harness.  A commit-fanout omission
    strands a prepared 2PC participant while the rest deliver —
    UNIFORMITY fails and shrinks to the minimal (begin, omit) script —
    and Bernstein CTP's cooperative termination repairs the identical
    schedule."""
    from partisan_tpu import faults as faults_mod
    from partisan_tpu.prop_models import AtomicCommitSystem

    def script_for(sys):
        # begin at node 0; the omission lands AFTER the votes return but
        # BEFORE the commit fan-out reaches node 4 (rounds_between=2
        # puts the cut at round ~2 of the transaction, mid-handshake).
        return [
            sys.begin_command(0, 0, 77),
            Command(name="omit_edge", args=(0, 4), kind="fault",
                    apply=lambda c, s: s._replace(
                        faults=faults_mod.inject_partition(
                            s.faults, [0], [4]))),
        ]

    sys2pc = AtomicCommitSystem(variant="lampson_2pc")
    h2pc = Harness(system=sys2pc, n_runs=1)
    script = script_for(sys2pc)
    assert not h2pc._execute(script), \
        "2PC should strand the cut participant (blocking)"
    shrunk = h2pc._shrink(script)
    assert len(shrunk) == 2, shrunk     # both commands required

    sysctp = AtomicCommitSystem(variant="bernstein_ctp")
    hctp = Harness(system=sysctp, n_runs=1)
    assert hctp._execute(script_for(sysctp)), \
        "CTP's decision_request should repair the stranded participant"


def test_atomic_commit_random_runs_hold_safety_for_ctp():
    """Random command sequences under the crash fault model: CTP keeps
    atomic-commit safety within the tolerance budget."""
    from partisan_tpu.prop_models import AtomicCommitSystem

    sys = AtomicCommitSystem(variant="bernstein_ctp", seed=3)
    res = Harness(
        system=sys,
        fault_model=CrashFaultModel(tolerance=1, allow_crash=False),
        scheduler="finite_fault", n_runs=4, n_commands=5, seed=900).run()
    assert res.ok, res.render()
