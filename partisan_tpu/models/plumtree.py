"""Plumtree epidemic broadcast trees (partisan_plumtree_broadcast.erl).

Reference behavior: per-root EAGER/LAZY peer sets carve a spanning tree
out of the overlay. A broadcast eager-pushes down tree links; receiving a
duplicate moves the sender to lazy and sends PRUNE (:843-857); lazy links
carry periodic I_HAVE adverts (flushed every lazy_tick, :990-1030); a
receiver missing an advertised message sends GRAFT, which re-activates the
link and re-sends the payload (:861-905); AAE exchanges with a random peer
every exchange_tick (:1040-1070).

TPU mapping (one tensor program per round, layered over ANY manager):

- the handler store (partisan_plumtree_broadcast_handler behaviour) is a
  bounded slot table ``data int32[n, B]`` merged by elementwise max — the
  monotonic-payload semantic of the default heartbeat handler
  (partisan_plumtree_backend.erl:191-260): a slot's payload is a version
  counter, re-broadcasts bump it and re-propagate,
- eager/lazy sets become ``pruned bool[n, B, K]`` flags over the overlay's
  K neighbor slots: eager(b, k) = link k alive and not pruned for tree b.
  The reference keys trees by broadcast ROOT; we key by broadcast slot
  (identical while roots are distinct — a per-root tree cache is a later
  optimization). Overlay churn invalidates flags per link slot, which is
  the membership-update ``neighbors_down`` pruning (:910-950),
- per-round emission is bounded: ``push_slots`` fresh slots per node per
  round (excess carried over in ``need_push``) and ``lazy_cap`` I_HAVEs
  per lazy tick — the sim analogue of mailbox backpressure; I_HAVEs repeat
  every tick until acked by GRAFT or IGNORED_I_HAVE, the reference's
  outstanding-ETS retransmission contract (:880-905).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import Array

from partisan_tpu import faults as faults_mod
from partisan_tpu import managers as managers_mod
from partisan_tpu import types as T
from partisan_tpu.comm import LocalComm
from partisan_tpu.config import BROADCAST_CHANNEL, Config
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.ops import msg as msg_ops
from partisan_tpu.ops import rng

_TAG_AAE = 401
_AAE_EDGE_TAG = 402


class PlumtreeState(NamedTuple):
    data: Array          # int32[n, B] — handler store (version per slot)
    rround: Array        # int32[n, B] — tree hop distance of our copy
    pruned: Array        # bool[n, B, K] — link k demoted to lazy for tree b
    lazy_pending: Array  # bool[n, B, K] — outstanding i_have to link k
    need_push: Array     # bool[n, B] — fresh slot awaiting eager push
    push_src: Array      # int32[n, B] — eager parent (excluded from push)
    tree_nbrs: Array     # int32[n, K] — link occupants flags refer to


class Plumtree:
    name = "plumtree"

    def init(self, cfg: Config, comm: LocalComm) -> PlumtreeState:
        n, B = comm.n_local, cfg.max_broadcasts
        K = managers_mod.neighbor_width(cfg)
        return PlumtreeState(
            data=jnp.zeros((n, B), jnp.int32),
            rround=jnp.zeros((n, B), jnp.int32),
            pruned=jnp.zeros((n, B, K), jnp.bool_),
            lazy_pending=jnp.zeros((n, B, K), jnp.bool_),
            need_push=jnp.zeros((n, B), jnp.bool_),
            push_src=jnp.full((n, B), -1, jnp.int32),
            tree_nbrs=jnp.full((n, K), -1, jnp.int32),
        )

    # ------------------------------------------------------------------
    def step(self, cfg: Config, comm: LocalComm, state: PlumtreeState,
             ctx: RoundCtx, nbrs: Array) -> tuple[PlumtreeState, Array]:
        pt = cfg.plumtree
        W = cfg.msg_words
        n_local, B = state.data.shape
        K = nbrs.shape[1]
        S, L = pt.push_slots, pt.lazy_cap
        CH = cfg.channel_id(BROADCAST_CHANNEL)
        gids = comm.local_ids()

        # Overlay churn: a link slot with a new occupant sheds its flags
        # (neighbors_down/up membership handling, reference :910-950).
        changed = nbrs != state.tree_nbrs                       # [n, K]
        pruned0 = state.pruned & ~changed[:, None, :]
        lazyp0 = state.lazy_pending & ~changed[:, None, :]

        def per_node(me, nbrs_row, pruned, lazyp, data, rr, npu, psrc,
                     inbox_row):
            def mk(kind, dst, payload=()):
                return msg_ops.build(W, kind, me, dst, channel=CH,
                                     payload=payload)

            nomsg = jnp.zeros((W,), jnp.int32)

            def slot_of(src):
                hit = (nbrs_row == src) & (src >= 0)
                return jnp.where(hit.any(), jnp.argmax(hit), -1)

            # ---- inbox scan ---------------------------------------
            def handle(carry, msg):
                pruned, lazyp, data, rr, npu, psrc = carry
                kind = msg[T.W_KIND]
                src = msg[T.W_SRC]
                b = jnp.clip(msg[T.P0], 0, B - 1)
                ver = msg[T.P1]
                mr = msg[T.P2]
                ks = slot_of(src)
                ks_ok = ks >= 0
                ki = jnp.where(ks_ok, ks, 0)

                def b_gossip(pruned, lazyp, data, rr, npu, psrc):
                    fresh = ver > data[b]
                    data2 = data.at[b].max(ver)
                    rr2 = rr.at[b].set(jnp.where(fresh, mr + 1, rr[b]))
                    npu2 = npu.at[b].set(npu[b] | fresh)
                    psrc2 = psrc.at[b].set(jnp.where(fresh, src, psrc[b]))
                    # fresh: add_eager(sender); stale: demote sender + PRUNE
                    pr2 = pruned.at[b, ki].set(
                        jnp.where(ks_ok, ~fresh, pruned[b, ki]))
                    reply = jnp.where(fresh, nomsg,
                                      mk(T.MsgKind.PT_PRUNE, src,
                                         payload=(b,)))
                    return pr2, lazyp, data2, rr2, npu2, psrc2, reply

                def b_ihave(pruned, lazyp, data, rr, npu, psrc):
                    missing = ver > data[b]
                    pr2 = pruned.at[b, ki].set(
                        jnp.where(ks_ok & missing, False, pruned[b, ki]))
                    reply = jnp.where(
                        missing,
                        mk(T.MsgKind.PT_GRAFT, src, payload=(b, ver)),
                        mk(T.MsgKind.PT_IHAVE_ACK, src, payload=(b, ver)))
                    return pr2, lazyp, data, rr, npu, psrc, reply

                def b_graft(pruned, lazyp, data, rr, npu, psrc):
                    pr2 = pruned.at[b, ki].set(
                        jnp.where(ks_ok, False, pruned[b, ki]))
                    lz2 = lazyp.at[b, ki].set(
                        jnp.where(ks_ok, False, lazyp[b, ki]))
                    reply = jnp.where(
                        data[b] > 0,
                        mk(T.MsgKind.PT_GOSSIP, src,
                           payload=(b, data[b], rr[b])),
                        nomsg)
                    return pr2, lz2, data, rr, npu, psrc, reply

                def b_prune(pruned, lazyp, data, rr, npu, psrc):
                    pr2 = pruned.at[b, ki].set(
                        jnp.where(ks_ok, True, pruned[b, ki]))
                    return pr2, lazyp, data, rr, npu, psrc, nomsg

                def b_ack(pruned, lazyp, data, rr, npu, psrc):
                    lz2 = lazyp.at[b, ki].set(
                        jnp.where(ks_ok, False, lazyp[b, ki]))
                    return pruned, lz2, data, rr, npu, psrc, nomsg

                def b_noop(pruned, lazyp, data, rr, npu, psrc):
                    return pruned, lazyp, data, rr, npu, psrc, nomsg

                branches = [b_gossip, b_ihave, b_graft, b_prune, b_ack,
                            b_noop]
                idx = jnp.where(
                    (kind >= T.MsgKind.PT_GOSSIP)
                    & (kind <= T.MsgKind.PT_IHAVE_ACK),
                    kind - T.MsgKind.PT_GOSSIP, len(branches) - 1)
                *carry2, reply = jax.lax.switch(
                    idx, branches, pruned, lazyp, data, rr, npu, psrc)
                return tuple(carry2), reply

            (pruned, lazyp, data, rr, npu, psrc), replies = jax.lax.scan(
                handle, (pruned, lazyp, data, rr, npu, psrc), inbox_row)

            # ---- eager push: up to S carried-over fresh slots ------
            pend = npu & (data > 0)
            prio = jnp.where(pend, B - jnp.arange(B), 0)
            pv, sel = jax.lax.top_k(prio, S)
            sel_ok = pv > 0

            def push_one(b, ok):
                eager = (nbrs_row >= 0) & ~pruned[b] & (nbrs_row != psrc[b])
                dst = jnp.where(ok & eager, nbrs_row, -1)
                msgs = jax.vmap(
                    lambda d: mk(T.MsgKind.PT_GOSSIP, d,
                                 payload=(b, data[b], rr[b])))(dst)
                lazy_new = ok & (nbrs_row >= 0) & pruned[b]
                return msgs, lazy_new

            push_msgs, lazy_new = jax.vmap(push_one)(sel, sel_ok)
            lazyp = lazyp.at[sel].set(lazyp[sel] | lazy_new)
            npu = npu.at[sel].set(jnp.where(sel_ok, False, npu[sel]))

            # ---- lazy tick: flush up to L outstanding i_haves ------
            fire = (ctx.rnd + me) % cfg.lazy_tick_every == 0
            flat = (lazyp & (nbrs_row >= 0)[None, :]).reshape(B * K)
            lprio = jnp.where(flat & fire, B * K - jnp.arange(B * K), 0)
            lv, li = jax.lax.top_k(lprio, L)
            bi, kix = li // K, li % K
            ihave_msgs = jax.vmap(
                lambda ok, b, k: mk(T.MsgKind.PT_IHAVE,
                                    jnp.where(ok, nbrs_row[k], -1),
                                    payload=(b, data[b])))(lv > 0, bi, kix)

            emitted = jnp.concatenate(
                [replies, push_msgs.reshape(-1, W), ihave_msgs])
            return pruned, lazyp, data, rr, npu, psrc, emitted

        (pruned, lazyp, data, rr, npu, psrc, emitted) = jax.vmap(per_node)(
            gids, nbrs, pruned0, lazyp0, state.data, state.rround,
            state.need_push, state.push_src, ctx.inbox.data)

        # ---- AAE exchange tick (handler exchange, :1040-1070): push the
        # whole store to one random peer on the monotonic state lane.  The
        # reference exchange is a session between two nodes; the one-way
        # periodic push converges identically under symmetric firing.
        if pt.aae:
            fires = ((ctx.rnd + gids) % cfg.exchange_tick_every == 0) \
                    & ctx.alive

            def pick(key, row, fire):
                slots = rng.choice_slots(
                    rng.subkey(key, _TAG_AAE), row >= 0, 1)
                t = jnp.where(slots >= 0, row[slots], jnp.int32(-1))
                return jnp.where(fire, t, jnp.int32(-1))

            tgt = jax.vmap(pick)(ctx.keys, nbrs, fires)    # [n, 1]
            tgt = faults_mod.filter_edges(
                ctx.faults, gids, tgt, cfg.seed, ctx.rnd, _AAE_EDGE_TAG)
            pulled = comm.push_max(data, tgt)
            data = jnp.maximum(data, jnp.where(ctx.alive[:, None], pulled, 0))

        # Crash-stopped nodes are frozen and silent.
        dead = ~ctx.alive

        def keep(new, old):
            return jnp.where(
                dead.reshape((-1,) + (1,) * (new.ndim - 1)), old, new)

        emitted = emitted.at[..., T.W_KIND].set(
            jnp.where(dead[:, None], 0, emitted[..., T.W_KIND]))
        new_state = PlumtreeState(
            data=keep(data, state.data),
            rround=keep(rr, state.rround),
            pruned=keep(pruned, state.pruned),
            lazy_pending=keep(lazyp, state.lazy_pending),
            need_push=keep(npu, state.need_push),
            push_src=keep(psrc, state.push_src),
            tree_nbrs=keep(nbrs, state.tree_nbrs),
        )
        return new_state, emitted

    # ---- scenario helpers (broadcast/2, partisan.erl:1556) -----------
    def broadcast(self, state: PlumtreeState, node: int, slot: int,
                  version: int = 1) -> PlumtreeState:
        return state._replace(
            data=state.data.at[node, slot].max(version),
            need_push=state.need_push.at[node, slot].set(True),
            push_src=state.push_src.at[node, slot].set(-1),
        )

    def coverage(self, state: PlumtreeState, alive: Array, slot: int,
                 version: int = 1) -> Array:
        have = (state.data[:, slot] >= version) & alive
        return jnp.sum(have) / jnp.maximum(jnp.sum(alive), 1)

    def eager_degree(self, state: PlumtreeState, slot: int) -> Array:
        """Mean eager out-degree for a tree — flood = overlay degree,
        converged tree ~ spanning-tree degree (debug_get_tree analogue,
        partisan_plumtree_broadcast.erl:179-188)."""
        live = state.tree_nbrs >= 0
        eager = live & ~state.pruned[:, slot, :]
        return jnp.sum(eager) / state.data.shape[0]
