"""Checkpoint / resume of cluster state (SURVEY.md §5.4).

The reference persists its critical state continuously: the membership
CRDT to ``<data_dir>/default_peer_service/cluster_state`` on every
mutation (partisan_full_membership_strategy.erl:289-330), the causality
backend's clock/order-buffer via ``write_state``
(partisan_causality_backend.erl:218, :243), and test traces via dets
(partisan_trace_file.erl).

The sim's entire cluster lives in one ``ClusterState`` pytree, so a
checkpoint is a snapshot of its leaves (the "jax checkpointing of the
cluster-state tensors" the survey prescribes).  Restore rebuilds the
pytree against a structural template — typically ``cluster.init()`` —
which also revalidates that the checkpoint matches the configuration.

Format: one ``.npz`` per checkpoint (leaf arrays + round number), plus
``latest``-by-round discovery over a directory, supporting the
crash/restart cycle the reference's re-join path exercises
(partisan_full_membership_strategy.erl load-from-disk at init).

Crash-safety hardening (the soak engine's contract, soak.py):

- **atomic writes** — every save lands in a same-directory temp file
  first and is published with ``os.replace``, so a writer killed
  mid-checkpoint can never leave a half-written ``.npz`` under the
  canonical name (the reference's dets files get the same guarantee
  from dets repair; an interrupted sim save must not poison the resume
  path the minute-mark fault relies on, tools/MINUTE_FAULT.md),
- **config fingerprint** — ``save(..., cfg=...)`` stores a digest of
  the full Config (including the wire-word layout and storage dtypes,
  which PR 6 made config-dependent) so a restore against a drifted
  configuration fails loudly even when the leaf shapes happen to agree.
  The fingerprint is RESIZE-AWARE (ISSUE 15): ``n_nodes`` is excluded
  from the digest — width is validated STRUCTURALLY (leaf shapes, and
  the restored ``n_active`` operand) instead, so a snapshot taken at
  one capacity restores into a wider program (``resize=True``) and an
  elastic run resumes at a different active width under the same
  program.  Version-3 files also store the full config FIELD TABLE, so
  a fingerprint mismatch names the drifted fields instead of printing
  two truncated hashes,
- **round validation** — the state's round counter is stored beside the
  leaves; ``restore`` cross-checks it against the restored ``rnd`` leaf
  and (optionally) a caller-expected round,
- **corruption detection** — a truncated or bit-flipped file raises
  :class:`CheckpointError` with a clear message instead of a bare
  zipfile/zlib traceback (numpy's zip container CRC-checks each member;
  we surface those failures and the missing-member case uniformly).
"""

from __future__ import annotations

import hashlib
import os
import re
import tempfile
import zipfile
import zlib

import jax
import numpy as np

# Version 2 added the fingerprint/round/wire-layout metadata; version 3
# makes the fingerprint resize-aware (width-free) and stores the config
# field table + the saving width for structural validation and
# field-by-field drift diagnostics.  Version 1 files (leaves only)
# remain restorable — their extra validation is simply unavailable;
# version 2 files validate against the LEGACY (width-inclusive)
# fingerprint, so they predate resizes but never false-fail.
FORMAT_VERSION = 3
_COMPAT_VERSIONS = (1, 2, 3)
_NAME = re.compile(r"^ckpt_(\d+)\.npz$")


class CheckpointError(ValueError):
    """A checkpoint could not be restored: corrupt/truncated file,
    configuration drift, or a round/template mismatch."""


class CheckpointCorruptError(CheckpointError):
    """The file itself is damaged (torn write, bit flip, not a zip).
    Distinct from drift/mismatch because ``restore_latest`` may fall
    back to an OLDER intact checkpoint on corruption — but never
    across config drift (older files would mask the real problem)."""


_N_NODES_RE = re.compile(r"\bn_nodes=\d+")


def _wire_desc(cfg) -> str:
    wire = cfg.wire_layout
    if isinstance(wire, tuple):
        return ",".join(str(np.dtype(d)) for d in wire)
    return f"int32x{wire}"


def config_fingerprint(cfg) -> str:
    """Stable RESIZE-AWARE digest of a Config — including the resolved
    wire layout (word count + per-word storage dtypes), which
    determines every wire buffer's shape and dtype, but EXCLUDING
    ``n_nodes``: width is a runtime quantity now (the elastic resize
    paths move ``n_active``, and a narrower snapshot may prefix-embed
    into a wider program — ``restore(resize=True)``), so it is
    validated structurally (leaf shapes + the saved width metadata)
    instead of poisoning the digest.  Every OTHER drift still fails
    loudly — a seed or cadence change keeps all shapes, which the
    shape check alone would miss."""
    blob = _N_NODES_RE.sub("n_nodes=*", repr(cfg), count=1)
    blob = f"{blob}|wire={_wire_desc(cfg)}".encode()
    return hashlib.sha256(blob).hexdigest()


# Config fields added DURING the version-2 era (v2 shipped in PR 7 and
# was only bumped by PR 15), at their default reprs, NEWEST FIRST: a
# v2 file's stored digest was computed over a repr without the fields
# that postdate it, so the legacy validation strips these groups
# progressively and accepts a match at ANY era (a config actually
# USING one of these features postdates the file that lacks its
# segment and can never match it, so its mismatch is correct, not a
# false failure).
_POST_V2_FIELD_SEGMENTS = (
    # PR 15: elastic + ingress lanes
    (", elastic=False, elastic_ring=16",
     ", ingress=IngressConfig(enabled=False, slots=8, ring_cap=4096, "
     "quota=256, payload_op=91)"),
    # PR 14: fleet runner operands
    (", salt_operand=False", ", fleet_width=0"),
    # PR 12: traffic plane
    (", traffic=TrafficConfig(enabled=False, rate_x1000=500, "
     "burst_max=4, zipf_s=1.0, hot_skew=0, channel='broadcast', "
     "churn=False, ring=64)",),
)


def legacy_fingerprints(cfg) -> set[str]:
    """Every version-2-era (width-inclusive) digest this config could
    have been saved under: the post-v2 field groups stripped at their
    defaults, one era at a time (newest first — a file written between
    two additions carries the older fields but not the newer).
    ``restore`` accepts a v2 file whose stored digest matches ANY era,
    so old files under an identical logical config never false-fail
    (tests/test_elastic.py pins the stripped form)."""
    out = set()
    blob = repr(cfg)
    for group in _POST_V2_FIELD_SEGMENTS:
        for seg in group:
            blob = blob.replace(seg, "", 1)
        out.add(hashlib.sha256(
            f"{blob}|wire={_wire_desc(cfg)}".encode()).hexdigest())
    return out


def legacy_fingerprint(cfg) -> str:
    """The LATEST v2-era digest (only the newest post-v2 group
    stripped) — what a file saved just before the v3 bump stores."""
    blob = repr(cfg)
    for seg in _POST_V2_FIELD_SEGMENTS[0]:
        blob = blob.replace(seg, "", 1)
    return hashlib.sha256(
        f"{blob}|wire={_wire_desc(cfg)}".encode()).hexdigest()


def config_fields(cfg) -> dict:
    """Flat ``{field: repr(value)}`` table of a Config — stored beside
    the fingerprint (v3) so a mismatch can be diffed field-by-field
    and the exception can NAME the drifted fields instead of printing
    two truncated hashes."""
    import dataclasses as _dc

    out = {f.name: repr(getattr(cfg, f.name))
           for f in _dc.fields(cfg)}
    out["<wire>"] = _wire_desc(cfg)
    return out


def _diff_fields(stored: dict, expected: dict) -> list[str]:
    """Human-readable per-field drift lines, sorted by field name."""
    out = []
    for k in sorted(set(stored) | set(expected)):
        s, e = stored.get(k, "<absent>"), expected.get(k, "<absent>")
        if s != e:
            out.append(f"{k}: checkpoint {s} != expected {e}")
    return out


def save(state, path: str | os.PathLike, cfg=None) -> None:
    """Snapshot a state pytree to ``path`` (.npz), atomically.

    The write goes to a same-directory temp file and is published with
    ``os.replace``, so a crash mid-write never leaves a torn file at
    ``path``.  Pass ``cfg`` to stamp the checkpoint with the config
    fingerprint (validated by ``restore`` when it, too, is given the
    config)."""
    path = os.fspath(path)
    leaves = jax.tree.leaves(state)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    meta = {"version": FORMAT_VERSION, "n_leaves": len(leaves)}
    rnd = getattr(state, "rnd", None)
    if rnd is not None:
        meta["rnd"] = np.int64(int(np.asarray(rnd)))
    if cfg is not None:
        import json as _json

        meta["fingerprint"] = np.str_(config_fingerprint(cfg))
        meta["config_desc"] = np.str_(_json.dumps(config_fields(cfg)))
        meta["n_nodes"] = np.int64(cfg.n_nodes)
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".tmp.",
        dir=os.path.dirname(path) or ".")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **meta, **arrays)
            # Flush to stable storage BEFORE publishing: os.replace is
            # atomic in the namespace, but an OS crash could otherwise
            # still publish a name pointing at torn contents.
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _open_checked(path):
    """np.load with the corruption cases mapped to CheckpointError."""
    try:
        return np.load(path)
    except (OSError, ValueError, zipfile.BadZipFile, zlib.error) as e:
        raise CheckpointCorruptError(
            f"checkpoint {path!r} is corrupt or truncated: {e}") from e


def _embed_leaf(i, a, t, old_n, new_n, jnp):
    """Resize-restore one leaf: equal shapes pass through; shapes that
    differ ONLY in axes where the checkpoint has ``old_n`` and the
    template ``new_n`` (the node axes — flight rings carry theirs at
    axis 1, dense partitions at both) prefix-embed into the template's
    init values, so rows ``[old_n, new_n)`` come up inert exactly as a
    fresh activation leaves them.  Anything else is real structural
    drift and raises."""
    tsh = np.shape(t)
    if a.shape == tsh:
        return jnp.asarray(a)
    if len(a.shape) == len(tsh):
        ok = all(sa == st or (sa == old_n and st == new_n)
                 for sa, st in zip(a.shape, tsh))
        if ok and old_n < new_n:
            out = np.asarray(t).copy()
            out[tuple(slice(0, s) for s in a.shape)] = a
            return jnp.asarray(out)
    raise CheckpointError(
        f"leaf {i}: checkpoint {a.shape}/{a.dtype} != template "
        f"{tsh}/{np.asarray(t).dtype} and the delta is not a node-axis "
        f"prefix growth {old_n}->{new_n}")


def restore(path: str | os.PathLike, like, cfg=None,
            expect_rnd: int | None = None, resize: bool = False):
    """Rebuild a checkpoint against the structural template ``like``
    (same treedef — e.g. ``cluster.init()``).  Shape/dtype mismatches
    raise, catching config drift between save and restore; ``cfg``
    additionally validates the stored config fingerprint (width-free
    since v3 — ``n_nodes`` is validated structurally instead, so an
    elastic snapshot resumes at any active width of the same program),
    and ``expect_rnd`` the stored round number.  On a fingerprint
    mismatch of a v3 file the stored config FIELD TABLE is diffed and
    the exception names the drifted fields.  ``resize=True``
    additionally accepts a NARROWER checkpoint into a wider template:
    node-axis leaves prefix-embed (rows beyond the saved width keep
    the template's init values — inert, exactly as activation expects)
    — the cross-capacity half of resize-safe checkpoints; the restored
    ``n_active`` operand still reports the saved active width.
    Corrupt or truncated files raise :class:`CheckpointError` (reading
    decompresses every member, so a torn tail or bit flip surfaces
    here, not later)."""
    import json as _json

    import jax.numpy as jnp

    if resize and cfg is None:
        raise ValueError(
            "restore(resize=True) needs cfg= — the prefix-embed is "
            "keyed on the template capacity (cfg.n_nodes) vs the "
            "checkpoint's saved width")
    path = os.fspath(path)
    treedef = jax.tree.structure(like)
    tmpl = jax.tree.leaves(like)
    with _open_checked(path) as z:
        if "version" not in z.files:
            raise CheckpointError(
                f"checkpoint {path!r} has no version field "
                "(not a partisan_tpu checkpoint?)")
        # Metadata members decompress on read: a bit flip confined to
        # one of them must still surface as CheckpointError, not a raw
        # zlib/zip traceback.
        try:
            version = int(z["version"])
            stored_fp = (str(z["fingerprint"])
                         if "fingerprint" in z.files else None)
            stored_desc = (str(z["config_desc"])
                           if "config_desc" in z.files else None)
            stored_n = (int(z["n_nodes"])
                        if "n_nodes" in z.files else None)
            n = int(z["n_leaves"])
            stored_rnd = int(z["rnd"]) if "rnd" in z.files else None
        except (KeyError, OSError, ValueError, zipfile.BadZipFile,
                zlib.error) as e:
            raise CheckpointCorruptError(
                f"checkpoint {path!r} is corrupt or truncated in its "
                f"metadata: {e}") from e
        if version not in _COMPAT_VERSIONS:
            raise CheckpointError(
                f"checkpoint version {version} not supported "
                f"(expected one of {_COMPAT_VERSIONS})")
        if cfg is not None and stored_fp is not None:
            # v3 stores the width-free digest; v2 stored a legacy
            # width-inclusive one computed over its ERA's repr —
            # accept any era's digest (legacy_fingerprints) so an old
            # file under an identical logical config never false-fails.
            if version >= 3:
                mismatch = stored_fp != config_fingerprint(cfg)
                want = config_fingerprint(cfg)
            else:
                mismatch = stored_fp not in legacy_fingerprints(cfg)
                want = legacy_fingerprint(cfg)
            if mismatch:
                detail = ""
                if stored_desc is not None:
                    drift = _diff_fields(_json.loads(stored_desc),
                                         config_fields(cfg))
                    # width is deliberately digest-free (validated
                    # structurally) — naming it as "drift" here would
                    # blame a difference v3 explicitly permits
                    drift = [d for d in drift
                             if not d.startswith("n_nodes:")]
                    if drift:
                        detail = ("; drifted fields: "
                                  + "; ".join(drift))
                    else:
                        detail = ("; no field-level drift found — "
                                  "fingerprint scheme mismatch?")
                raise CheckpointError(
                    f"checkpoint {path!r} was written under a different "
                    f"configuration (fingerprint {stored_fp[:12]}… != "
                    f"{want[:12]}…) — refusing to restore across config "
                    f"drift{detail}")
        if n != len(tmpl):
            raise CheckpointError(
                f"checkpoint has {n} leaves, template has {len(tmpl)} "
                f"(configuration changed since save?)")
        new_n = (cfg.n_nodes if cfg is not None else None)
        do_resize = (resize and stored_n is not None
                     and new_n is not None and stored_n != new_n)
        if do_resize and stored_n > new_n:
            raise CheckpointError(
                f"checkpoint {path!r} was saved at capacity {stored_n} "
                f"— cannot shrink into a {new_n}-wide template (scale "
                "in BEFORE snapshotting, then restore the narrow "
                "state)")
        leaves = []
        try:
            for i, t in enumerate(tmpl):
                a = z[f"leaf_{i}"]
                if a.dtype != np.asarray(t).dtype:
                    raise CheckpointError(
                        f"leaf {i}: checkpoint {a.shape}/{a.dtype} != "
                        f"template {np.shape(t)}/{np.asarray(t).dtype}")
                if do_resize:
                    leaves.append(_embed_leaf(i, a, t, stored_n, new_n,
                                              jnp))
                    continue
                if a.shape != np.shape(t):
                    hint = ""
                    if (stored_n is not None and new_n is not None
                            and stored_n != new_n):
                        hint = (f" (saved at capacity {stored_n}, "
                                f"template is {new_n}-wide — pass "
                                "resize=True to prefix-embed)")
                    raise CheckpointError(
                        f"leaf {i}: checkpoint {a.shape}/{a.dtype} != "
                        f"template {np.shape(t)}/{np.asarray(t).dtype}"
                        + hint)
                leaves.append(jnp.asarray(a))
        except (KeyError, OSError, ValueError, zipfile.BadZipFile,
                zlib.error) as e:
            if isinstance(e, CheckpointError):
                raise
            raise CheckpointCorruptError(
                f"checkpoint {path!r} is corrupt or truncated while "
                f"reading leaf {i}: {e}") from e
    out = jax.tree.unflatten(treedef, leaves)
    got_rnd = getattr(out, "rnd", None)
    if got_rnd is not None:
        got = int(np.asarray(got_rnd))
        if stored_rnd is not None and stored_rnd != got:
            raise CheckpointError(
                f"checkpoint {path!r} round metadata {stored_rnd} "
                f"disagrees with its rnd leaf {got} — file corrupt?")
        if expect_rnd is not None and got != int(expect_rnd):
            raise CheckpointError(
                f"checkpoint {path!r} holds round {got}, caller "
                f"expected round {int(expect_rnd)}")
    return out


# ---- step-numbered checkpoint directories ------------------------------

def save_step(state, ckpt_dir: str | os.PathLike, rnd: int,
              cfg=None) -> str:
    """Save as ``<dir>/ckpt_<round>.npz`` (atomic); returns the path."""
    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(os.fspath(ckpt_dir), f"ckpt_{int(rnd)}.npz")
    save(state, path, cfg=cfg)
    return path


def steps(ckpt_dir: str | os.PathLike) -> list[int]:
    """Rounds with a checkpoint in ``ckpt_dir``, ascending."""
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for f in os.listdir(ckpt_dir):
        m = _NAME.match(f)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def restore_latest(ckpt_dir: str | os.PathLike, like, cfg=None):
    """Load the newest INTACT checkpoint, or None if the directory is
    empty — the load-or-bootstrap decision of the reference's init
    (partisan_full_membership_strategy.erl:289-330).

    A corrupt newest file (a torn write published by an OS crash at
    exactly the wrong moment) falls back to the next-older checkpoint
    instead of permanently blocking resume; config drift or a round
    mismatch still raises — every older file would carry the same
    problem, and silently restoring stale pre-drift state would mask
    it."""
    all_steps = steps(ckpt_dir)
    if not all_steps:
        return None
    last_err: CheckpointCorruptError | None = None
    for rnd in reversed(all_steps):
        try:
            return restore(
                os.path.join(os.fspath(ckpt_dir), f"ckpt_{rnd}.npz"),
                like, cfg=cfg, expect_rnd=rnd)
        except CheckpointCorruptError as e:
            last_err = e
    raise CheckpointCorruptError(
        f"every checkpoint in {os.fspath(ckpt_dir)!r} is corrupt "
        f"(newest failure: {last_err})")
