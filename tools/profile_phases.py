"""Component-level timing of the bench round at scale.

The ablation profiler (profile_round.py) toggles config knobs on the
FULL round; this one times the round's pieces in ISOLATION — manager
quiet path, plumtree body, AAE stage, route/compaction, fault filter,
record builds — each as its own k-iteration ``lax.scan`` on a synthetic
settled overlay (ring active views).  Costs on this backend are
shape-determined (static shapes; only the lax.cond gates depend on
content), so a synthetic overlay prices the ops faithfully without a
multi-minute bootstrap.  Results drive the round-5 hot-path work; keep
findings in BENCH_NOTES.md.

Set ``PROFILE_TRACE_DIR=/tmp/trace`` to capture a ``jax.profiler``
trace of the timed executions (the profile_round.py convention, shared
via partisan_tpu/perfwatch.py — one parser, two CLIs) and print the
measured per-phase attribution as JSON lines on stderr.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# --cost --budgets re-traces the lint matrix, whose sharded entries
# need a multi-device host platform — the one shared pin
# (partisan_tpu/hostmesh.py); harmless on the TPU path (host-platform
# flag only).
from partisan_tpu.hostmesh import force_host_devices

force_host_devices()

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/tmp/partisan_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

K = 50


def main(n: int, plane_major: bool = True, tag: str = "") -> None:
    from partisan_tpu import faults as faults_mod
    from partisan_tpu.cluster import Cluster, ClusterState, Stats
    from partisan_tpu.config import Config, HyParViewConfig, PlumtreeConfig
    from partisan_tpu.managers.base import RoundCtx
    from partisan_tpu.managers.hyparview import HyParViewState
    from partisan_tpu.models.plumtree import Plumtree
    from partisan_tpu.ops import exchange, msg as msg_ops, rng

    cfg = Config(n_nodes=n, seed=1, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups",
                 max_broadcasts=8, inbox_cap=16, emit_compact=32,
                 timer_stagger=False, plane_major=plane_major,
                 hyparview=HyParViewConfig(isolation_window_ms=25_000),
                 plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4))
    model = Plumtree()
    cl = Cluster(cfg, model=model)
    comm = cl.comm
    mgr = cl.manager
    W = cfg.msg_words
    A = cfg.hyparview.active_max
    ids = jnp.arange(n, dtype=jnp.int32)

    # Synthetic settled overlay: ring active views (4 neighbors), a few
    # passive entries, heartbeat clocks fresh.
    def build_state():
        act = jnp.stack([(ids + 1) % n, (ids - 1) % n,
                         (ids + 2) % n, (ids - 2) % n], axis=1)
        act = jnp.concatenate(
            [act, jnp.full((n, A - 4), -1, jnp.int32)], axis=1)
        P = cfg.hyparview.passive_max
        pas = jnp.stack([(ids + 3 + i) % n for i in range(8)], axis=1)
        pas = jnp.concatenate(
            [pas, jnp.full((n, P - 8), -1, jnp.int32)], axis=1)
        mstate = HyParViewState(
            active=act, passive=pas,
            join_target=jnp.full((n,), -1, jnp.int32),
            leaving=jnp.zeros((n,), jnp.bool_),
            left=jnp.zeros((n,), jnp.bool_),
            reserved=jnp.zeros((n,), jnp.int32),
            joined=jnp.ones((n,), jnp.bool_),
            hb_epoch=jnp.zeros((n,), jnp.int32),
            hb_rnd=jnp.zeros((n,), jnp.int32), dist=())
        pstate = model.init(cfg, comm)
        pstate = pstate._replace(tree_nbrs=act)
        return mstate, pstate, act

    mstate, pstate, act = build_state()
    faults = faults_mod.none(n, cfg.resolved_partition_mode)
    inbox0 = exchange.empty_inbox(n, cfg.inbox_cap, cfg.wire_layout)

    def ctx_at(rnd):
        return RoundCtx(rnd=rnd, alive=faults.alive,
                        keys=rng.node_keys(cfg.seed, rnd, ids),
                        inbox=inbox0, faults=faults)

    argv = [a for a in sys.argv[1:] if not a.startswith("--")]
    only = argv[1] if len(argv) > 1 else None

    def timed(label, fn, carry):
        if only and only not in label.lower():
            return
        jfn = jax.jit(lambda c: jax.lax.scan(
            lambda cc, _: (fn(cc), None), c, None, length=K)[0])
        t0 = time.perf_counter()
        out = jfn(carry)
        s = jax.tree.leaves(out)[0]
        jax.device_get(jnp.sum(s))
        compile_s = time.perf_counter() - t0
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = jfn(carry)
            s = jax.tree.leaves(out)[0]
            jax.device_get(jnp.sum(s))
            best = min(best, time.perf_counter() - t0)
        print(f"{label:34s} {best / K * 1e3:7.2f} ms/iter  "
              f"(compile {compile_s:.0f}s)", flush=True)
        if tag:
            # --layout A/B series: machine-readable per-phase line
            print(f"profile_phases,layout={tag},n={n},"
                  f"phase={label.replace(' ', '_')},"
                  f"ms_per_iter={best / K * 1e3:.3f}",
                  file=sys.stderr, flush=True)

    # 1. manager step, quiet inbox (the convergence-phase manager cost):
    #    consecutive rounds so the shuffle cadence fires its real 1/10.
    def hv_quiet(c):
        st, rnd = c
        st2, _em = mgr.step(cfg, comm, st, ctx_at(rnd))
        return (st2, rnd + 1)

    timed("hv step quiet (cad 1/10)", hv_quiet, (mstate, jnp.int32(3)))

    # 2. manager step, never-firing cadence (pure quiet floor)
    def hv_quiet_nocad(c):
        st, rnd = c
        st2, _em = mgr.step(cfg, comm, st, ctx_at(rnd))
        return (st2, rnd + 10)

    timed("hv step quiet (cad never)", hv_quiet_nocad,
          (mstate, jnp.int32(3)))

    # 3. manager step with heartbeat machinery off
    cfg_nohb = dataclasses.replace(
        cfg, hyparview=HyParViewConfig(isolation_window_ms=25_000,
                                       heartbeat=False,
                                       auto_rejoin=False))

    def hv_quiet_nohb(c):
        st, rnd = c
        st2, _em = mgr.step(cfg_nohb, comm, st, ctx_at(rnd))
        return (st2, rnd + 10)

    timed("hv step quiet, hb+rejoin off", hv_quiet_nohb,
          (mstate, jnp.int32(3)))

    # 4. plumtree step, body active (broadcast in flight), AAE ticking
    def pt_active(c):
        st, rnd = c
        st2 = st._replace(need_push=st.need_push.at[0, 0].set(True))
        st3, _em = model.step(cfg, comm, st2, ctx_at(rnd), act)
        return (st3, rnd + 1)

    timed("pt step active (body+aae)", pt_active, (pstate, jnp.int32(3)))

    # 5. plumtree step, fully idle (both gates skip)
    def pt_idle(c):
        st, rnd = c
        st2, _em = model.step(cfg, comm, st, ctx_at(rnd), act)
        return (st2, rnd + 1)

    timed("pt step idle (gates skip)", pt_idle, (pstate, jnp.int32(3)))

    # 6. plumtree step, body active, AAE never firing
    cfg_noaae = dataclasses.replace(
        cfg, plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4, aae=False))

    def pt_active_noaae(c):
        st, rnd = c
        st2 = st._replace(need_push=st.need_push.at[0, 0].set(True))
        st3, _em = model.step(cfg_noaae, comm, st2, ctx_at(rnd), act)
        return (st3, rnd + 1)

    timed("pt step active, aae off", pt_active_noaae,
          (pstate, jnp.int32(3)))

    # 7. the wire stage: emission stack -> compact -> route, ~5% fill
    E = 71
    fill = np.zeros((n, E), np.int32)
    rs = np.random.RandomState(0)
    livemask = rs.rand(n, E) < 0.05
    fill[livemask] = 3
    kinds = jnp.asarray(fill)
    dsts = jnp.asarray(rs.randint(0, n, size=(n, E)), jnp.int32)
    base_em = msg_ops.build(cfg if cfg.plane_major else W, kinds,
                            ids[:, None], jnp.where(kinds != 0, dsts, -1))

    def wire(c):
        em, acc = c
        e = exchange.compact_emissions(em, cfg.emit_compact)
        ib = comm.route(e)
        return (em, acc + ib.count)

    timed("compact71->32 + route", wire,
          (base_em, jnp.zeros((n,), jnp.int32)))

    def route_only(c):
        em, acc = c
        ib = comm.route(em)
        return (em, acc + ib.count)

    timed("route 71 (no compact)", route_only,
          (base_em, jnp.zeros((n,), jnp.int32)))

    # 8. fault filter + monotonic shed over the full stack
    mono = jnp.asarray([c.monotonic for c in cfg.channels], jnp.bool_)

    def filt(c):
        em, rnd = c
        backed = jnp.zeros((n,), jnp.bool_)
        ch = jnp.clip(em[..., 3], 0, cfg.n_channels - 1)
        dstv = jnp.clip(em[..., 2], 0, n - 1)
        shed = mono[ch] & backed[dstv] & (em[..., 0] != 0)
        em2 = em.at[..., 0].set(jnp.where(shed, 0, em[..., 0]))
        em3 = faults_mod.filter_msgs(faults, em2, cfg.seed, rnd, 11)
        return (em3, rnd + 1)

    timed("shed + fault filter (71)", filt, (base_em, jnp.int32(3)))

    # 9. full round for reference (active broadcast), same instrument
    st_full = ClusterState(
        rnd=jnp.int32(3), faults=faults, inbox=inbox0, manager=mstate,
        model=pstate, delivery=(),
        stats=Stats(jnp.int32(0), jnp.int32(0), jnp.int32(0)),
        interpose=cl.interpose.init(cfg, comm) if cl.interpose else (),
        outbox=())

    def full(c):
        st = c
        st = st._replace(model=st.model._replace(
            need_push=st.model.need_push.at[0, 0].set(True)))
        from partisan_tpu.cluster import round_body
        return round_body(cfg, mgr, model, comm, st,
                          interpose=cl.interpose)

    timed("FULL round (active)", full, st_full)


def cost_census(n: int, budgets: bool = False,
                width_op: bool = False) -> int:
    """``--cost``: the STATIC round-cost census — trace the plain
    bench-config round at ``n`` abstractly (no device, no compile) and
    print the round-cost meter's per-phase rows as JSON lines plus one
    summary object (partisan_tpu/lint/cost.py; BENCH_NOTES' corrected
    cost model as a measured quantity).  ``--budgets`` additionally
    judges the pinned lint matrix budgets (cost_budgets.BUDGETS) and
    exits 1 on any over/stale finding — the CLI face of the tier-1
    ``round-cost-budget`` rule."""
    import json

    jax.config.update("jax_platforms", "cpu")
    from partisan_tpu.lint import cost as cost_mod

    prog = cost_mod.bench_round_program(n, width_operand=width_op)
    census = cost_mod.census_program(prog)
    rows = census.rows()
    for row in rows[:-1]:   # the trailing 'total' row is the summary
        print(json.dumps({"kind": "cost_phase", "n": n, **row}),
              flush=True)
    rc = 0
    out = {"kind": "cost", "n": n, "program": prog.name,
           **{k: v for k, v in rows[-1].items() if k != "phase"}}
    if budgets:
        from partisan_tpu.lint import matrix
        from partisan_tpu.lint.rules import round_cost_budget

        finds = []
        for p in matrix.default_matrix():
            finds += round_cost_budget(p)
        for f in finds:
            print(json.dumps({"kind": "cost_budget_finding",
                              "detail": f.detail,
                              "message": f.message}), flush=True)
        out["budget_verdict"] = "CLEAN" if not finds else "DIRTY"
        out["budget_findings"] = len(finds)
        rc = 0 if not finds else 1
    print(json.dumps(out), flush=True)
    return rc


USAGE = """usage: profile_phases.py [--layout] [--cost [--budgets]] [n] [only]

--layout: A/B the two wire layouts — interleaved legacy
(Config.plane_major=False) vs plane-major — over every phase, emitting
a machine-readable per-phase series on stderr
(`profile_phases,layout=...,phase=...,ms_per_iter=...`).

--cost: STATIC per-phase round-cost census (gather/scatter eqns,
fetched scalars, materialized [n,.,.] intermediate bytes) of the plain
bench round at n (default 32768) — jaxpr-level, runs with NO device.
--budgets additionally judges the pinned lint cost budgets and exits 1
on any over/stale finding.  --width-op traces with Config.width_operand
like the real bench program (bench.py's cost card does)."""


if __name__ == "__main__":
    if "--help" in sys.argv or "-h" in sys.argv:
        print(USAGE)
        print(__doc__.strip())
    else:
        argv = [a for a in sys.argv[1:]
                if a not in ("--layout", "--cost", "--budgets",
                             "--width-op")]
        layout_ab = "--layout" in sys.argv
        size = int(argv[0]) if argv else 32_768
        if "--cost" in sys.argv:
            raise SystemExit(cost_census(
                size, budgets="--budgets" in sys.argv,
                width_op="--width-op" in sys.argv))
        # PROFILE_TRACE_DIR rides the same capture + trace-parsing core
        # as profile_round.py (partisan_tpu/perfwatch.py): a no-op when
        # unset, else the isolated-phase executions are captured and
        # attributed to round.* scopes (the FULL-round reference run
        # carries them) on stderr.
        from partisan_tpu import perfwatch

        with perfwatch.capture() as trace_dir:
            if layout_ab:
                main(size, plane_major=False, tag="interleaved")
                main(size, plane_major=True, tag="plane")
            else:
                main(size)
        if trace_dir:
            import json

            for name, slot in sorted(
                    perfwatch.attribute(trace_dir).items()):
                print(json.dumps({"kind": "perf_phase", "phase": name,
                                  **slot}), file=sys.stderr, flush=True)
