"""In-sim vectorized gen_server (partisan_tpu.otp.gen_sim): the
partisan_gen call protocol (priv/otp/24/partisan_gen.erl:360-400) run
INSIDE the jitted round — one counter gen_server per node, stacked with
the monitor service, calls riding the event exchange.

Covers the call / timeout / DOWN triad the reference's call path
implements, plus cast, server serialization order, and stop semantics.
"""

import pytest

from partisan_tpu import faults as faults_mod
from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config
from partisan_tpu.models.stack import Stack
from partisan_tpu.otp.gen_sim import (
    FN_GET, FN_INCR, FN_STOP, GenServerService)

N = 6


def build(**cfg_kw):
    svc = GenServerService()
    stack = Stack([svc])
    cfg = Config(n_nodes=N, seed=17, inbox_cap=48, **cfg_kw)
    cl = Cluster(cfg, model=stack)
    st = cl.init()
    for i in range(1, N):
        st = st._replace(manager=cl.manager.join(cfg, st.manager, i, 0))
    st = cl.steps(st, 5)
    return cl, stack, svc, st


def _sub(stack, st):
    return stack.sub(st.model, 0)


def _put(stack, st, gs):
    return st._replace(model=stack.replace_sub(st.model, 0, gs))


def test_call_roundtrip_and_server_state_persists():
    cl, stack, svc, st = build()
    gs, r1 = svc.call(_sub(stack, st), caller=2, dst=4, fn=FN_INCR,
                      arg=5, timeout_rounds=10, now=int(st.rnd))
    st = _put(stack, st, gs)
    st = cl.steps(st, 4)
    assert svc.response(_sub(stack, st), 2, r1) == ("ok", 5)
    # state persisted across calls: second incr sees the first
    gs = svc.free(_sub(stack, st), 2, r1)
    gs, r2 = svc.call(gs, caller=2, dst=4, fn=FN_INCR, arg=3,
                      timeout_rounds=10, now=int(st.rnd))
    st = _put(stack, st, gs)
    st = cl.steps(st, 4)
    assert svc.response(_sub(stack, st), 2, r2) == ("ok", 8)


def test_same_round_calls_serialize_in_mailbox_order():
    """Two calls landing in one round apply in inbox order; each reply
    carries the counter as of ITS queue position (the gen_server
    serialization the prefix-scan reproduces)."""
    cl, stack, svc, st = build()
    gs = _sub(stack, st)
    gs, ra = svc.call(gs, caller=1, dst=4, fn=FN_INCR, arg=10,
                      timeout_rounds=10, now=int(st.rnd))
    gs, rb = svc.call(gs, caller=1, dst=4, fn=FN_INCR, arg=7,
                      timeout_rounds=10, now=int(st.rnd))
    st = _put(stack, st, gs)
    st = cl.steps(st, 4)
    va = svc.response(_sub(stack, st), 1, ra)[1]
    vb = svc.response(_sub(stack, st), 1, rb)[1]
    assert {va, vb} == {10, 17}      # distinct prefix values, total 17


def test_get_observes_earlier_incr_same_round():
    cl, stack, svc, st = build()
    gs = _sub(stack, st)
    gs, ri = svc.call(gs, caller=3, dst=5, fn=FN_INCR, arg=9,
                      timeout_rounds=10, now=int(st.rnd))
    gs, rg = svc.call(gs, caller=3, dst=5, fn=FN_GET, arg=0,
                      timeout_rounds=10, now=int(st.rnd))
    st = _put(stack, st, gs)
    st = cl.steps(st, 4)
    assert svc.response(_sub(stack, st), 3, ri) == ("ok", 9)
    # the GET queued after the INCR (same sender FIFO) sees 9
    assert svc.response(_sub(stack, st), 3, rg) == ("ok", 9)


def test_cast_is_async_no_reply_slot():
    cl, stack, svc, st = build()
    gs = svc.cast(_sub(stack, st), caller=1, dst=4, fn=FN_INCR, arg=6)
    st = _put(stack, st, gs)
    st = cl.steps(st, 3)
    gs = _sub(stack, st)
    assert int(gs.status[1].sum()) == 0          # slot freed, no reply
    assert int(gs.counter[4]) == 6               # but it executed
    gs, r = svc.call(gs, caller=1, dst=4, fn=FN_GET, arg=0,
                     timeout_rounds=10, now=int(st.rnd))
    st = _put(stack, st, gs)
    st = cl.steps(st, 4)
    assert svc.response(_sub(stack, st), 1, r) == ("ok", 6)


def test_call_times_out_on_partition():
    """No reply within the window -> timeout (the demonitor path);
    late replies can no longer pair with the demonitored ref."""
    cl, stack, svc, st = build()
    st = st._replace(faults=faults_mod.inject_partition(
        st.faults, [2], [4]))
    gs, ref = svc.call(_sub(stack, st), caller=2, dst=4, fn=FN_INCR,
                       arg=1, timeout_rounds=5, now=int(st.rnd))
    st = _put(stack, st, gs)
    st = cl.steps(st, 8)
    assert svc.response(_sub(stack, st), 2, ref) == ("timeout", None)


def test_call_aborts_with_down_when_destination_dies():
    """Destination crashes mid-call -> DOWN, not a hang until timeout
    (the partisan_gen monitor path)."""
    cl, stack, svc, st = build()
    st = st._replace(faults=faults_mod.crash(st.faults, 4))
    gs, ref = svc.call(_sub(stack, st), caller=2, dst=4, fn=FN_INCR,
                       arg=1, timeout_rounds=50, now=int(st.rnd))
    st = _put(stack, st, gs)
    st = cl.steps(st, 3)
    assert svc.response(_sub(stack, st), 2, ref) == ("down", None)


def test_stop_terminates_server_requests_after_unserved():
    cl, stack, svc, st = build()
    gs = _sub(stack, st)
    gs, rs = svc.call(gs, caller=1, dst=4, fn=FN_STOP, arg=0,
                      timeout_rounds=10, now=int(st.rnd))
    st = _put(stack, st, gs)
    st = cl.steps(st, 4)
    assert svc.response(_sub(stack, st), 1, rs) == ("ok", 0)
    # further calls to the stopped server never answer -> timeout
    gs, r2 = svc.call(_sub(stack, st), caller=1, dst=4, fn=FN_GET,
                      arg=0, timeout_rounds=5, now=int(st.rnd))
    st = _put(stack, st, gs)
    st = cl.steps(st, 8)
    assert svc.response(_sub(stack, st), 1, r2) == ("timeout", None)


def test_call_table_overflow_raises():
    cl, stack, svc, st = build()
    gs = _sub(stack, st)
    for i in range(svc.cap):
        gs, _ = svc.call(gs, 0, 1, FN_INCR, i, 10, int(st.rnd))
    with pytest.raises(RuntimeError):
        svc.call(gs, 0, 1, FN_INCR, 99, 10, int(st.rnd))
