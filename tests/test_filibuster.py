"""Filibuster model-checker tests (reference test/filibuster_SUITE.erl):
the checker finds a single-omission counterexample against unacked direct
mail (no retransmission => reliable broadcast fails), and certifies the
acked variant against the same fault budget (retransmission repairs every
single omission)."""

from partisan_tpu import filibuster
from partisan_tpu.cluster import Cluster
from partisan_tpu.models.direct_mail import DirectMail
from tests.support import fm_config, boot_fullmesh

N = 6
HORIZON = 12


def _build_fn(acked):
    model = DirectMail(acked=acked)

    def build(interp):
        cfg = fm_config(N, seed=17, ack_cap=8 if acked else 0)
        cl = Cluster(cfg, model=model, interpose=interp)
        st = boot_fullmesh(cl)
        st = st._replace(model=model.broadcast(st.model, 0, 0))
        return cl, st

    return model, build


def _assertion(model):
    # Reliable broadcast: every (alive) node eventually delivers.
    def check(cl, st):
        return float(model.coverage(st.model, st.faults.alive, 0)) == 1.0
    return check


def test_finds_counterexample_for_unacked_direct_mail():
    model, build = _build_fn(acked=False)
    checker = filibuster.Checker(
        build=build, horizon=HORIZON, assertion=_assertion(model),
        candidate=filibuster.app_messages, max_faults=1)
    res = checker.run()
    assert not res.passed
    assert len(res.counterexample.schedule) == 1  # shrunk to minimal
    assert "omit" in res.render() and "APP" in res.render()


def test_certifies_acked_direct_mail_single_omission():
    model, build = _build_fn(acked=True)
    checker = filibuster.Checker(
        build=build, horizon=HORIZON, assertion=_assertion(model),
        candidate=filibuster.app_messages, max_faults=1)
    res = checker.run()
    assert res.passed, res.render()
    assert res.executions >= N  # base + one per first-mailing candidate
    assert "PASSED" in res.render()


def test_budget_two_prunes_and_bounds():
    model, build = _build_fn(acked=False)
    checker = filibuster.Checker(
        build=build, horizon=HORIZON, assertion=_assertion(model),
        candidate=filibuster.app_messages, max_faults=2,
        max_executions=30)
    res = checker.run()
    # Still fails at depth 1 — deeper budget must not hide the minimal cex.
    assert not res.passed
    assert len(res.counterexample.schedule) == 1


def test_iter_schedules_enumeration():
    cands = [(0, 1, 0), (0, 2, 0), (1, 1, 1)]
    scheds = list(filibuster.iter_schedules(cands, 2))
    assert frozenset({(0, 1, 0)}) in scheds
    assert frozenset({(0, 1, 0), (1, 1, 1)}) in scheds
    assert all(len(s) <= 2 for s in scheds)
    assert len(scheds) == 3 + 3


def test_annotation_pruning_reduces_candidates():
    """Causality annotations prune omission candidates that cannot affect
    the target kind (the partisan_analysis -> schedule_valid_causality
    pipeline)."""
    from partisan_tpu import analysis

    model, build = _build_fn(acked=True)
    # Record a golden run to derive the reaction graph.
    cl, st = build(None)
    _, cap = cl.record(st, HORIZON)
    from partisan_tpu import trace as trace_mod
    tr = trace_mod.from_capture(cap)
    g = analysis.reaction_graph(tr)

    # Ack-retransmission implication: losing an ACK re-triggers APP
    # retransmission, so ACK must NOT be prunable against target APP
    # (the unsound-pruning regression).
    assert "APP" in g.get("ACK", set())

    def any_kind(ev):
        return ev.kind_name in ("APP", "ACK", "PING", "PONG")

    pruned = filibuster.Checker(
        build=build, horizon=HORIZON, assertion=_assertion(model),
        candidate=any_kind, max_faults=1, max_executions=5,
        reaction=g, target_kinds=("APP",))
    base_p = pruned._execute(frozenset())
    cp = pruned._candidates(base_p.trace)
    kinds_kept = {e.kind_name for e in base_p.trace.events()
                  if (e.rnd, e.src, e.slot) in set(cp)}
    assert "APP" in kinds_kept and "ACK" in kinds_kept
    # Pruning logic itself: a kind with no path to the target is skipped.
    pruned.reaction = {"PONG": set(), **g}
    pruned._closure = None
    assert not pruned._relevant_kind("PONG")
    assert pruned._relevant_kind("ACK") and pruned._relevant_kind("APP")
