"""Erlang External Term Format codec (the ``term_to_binary`` wire format,
reference partisan_util.erl:171-183 encodes all partisan frames with it).

Implements the subset the bridge protocol needs — atoms, integers,
floats, tuples, lists, binaries, maps, strings — of the ETF spec
(format version 131).  Erlang atoms map to :class:`Atom`; improper lists
are not supported (the bridge protocol doesn't use them).

This is a clean-room implementation from the published format: each term
is one tag byte followed by a fixed layout.
"""

from __future__ import annotations

import struct

VERSION = 131

# tags (ETF spec)
SMALL_INTEGER_EXT = 97
INTEGER_EXT = 98
FLOAT_NEW_EXT = 70
ATOM_UTF8_EXT = 118
SMALL_ATOM_UTF8_EXT = 119
SMALL_TUPLE_EXT = 104
LARGE_TUPLE_EXT = 105
NIL_EXT = 106
STRING_EXT = 107
LIST_EXT = 108
BINARY_EXT = 109
SMALL_BIG_EXT = 110
MAP_EXT = 116


class Atom(str):
    """An Erlang atom (distinct from binaries/strings)."""

    __slots__ = ()

    def __repr__(self) -> str:  # 'ok -> Atom('ok')
        return f"Atom({str.__repr__(self)})"


# Common protocol atoms.
OK = Atom("ok")
ERROR = Atom("error")
TRUE = Atom("true")
FALSE = Atom("false")
UNDEFINED = Atom("undefined")


# ---------------------------------------------------------------------------
# encode
# ---------------------------------------------------------------------------

def encode(term) -> bytes:
    """term_to_binary/1."""
    return bytes([VERSION]) + _enc(term)


def _enc(t) -> bytes:
    if isinstance(t, Atom):
        b = str(t).encode("utf-8")
        if len(b) < 256:
            return bytes([SMALL_ATOM_UTF8_EXT, len(b)]) + b
        return bytes([ATOM_UTF8_EXT]) + struct.pack(">H", len(b)) + b
    if isinstance(t, bool):
        return _enc(TRUE if t else FALSE)
    if isinstance(t, int):
        if 0 <= t <= 255:
            return bytes([SMALL_INTEGER_EXT, t])
        if -(1 << 31) <= t < (1 << 31):
            return bytes([INTEGER_EXT]) + struct.pack(">i", t)
        # SMALL_BIG_EXT: sign + little-endian magnitude bytes
        sign = 1 if t < 0 else 0
        mag = abs(t)
        digits = b""
        while mag:
            digits += bytes([mag & 0xFF])
            mag >>= 8
        if len(digits) > 255:
            raise ValueError("integer too large for SMALL_BIG_EXT")
        return bytes([SMALL_BIG_EXT, len(digits), sign]) + digits
    if isinstance(t, float):
        return bytes([FLOAT_NEW_EXT]) + struct.pack(">d", t)
    if isinstance(t, tuple):
        if len(t) < 256:
            head = bytes([SMALL_TUPLE_EXT, len(t)])
        else:
            head = bytes([LARGE_TUPLE_EXT]) + struct.pack(">I", len(t))
        return head + b"".join(_enc(x) for x in t)
    if isinstance(t, list):
        if not t:
            return bytes([NIL_EXT])
        return (bytes([LIST_EXT]) + struct.pack(">I", len(t))
                + b"".join(_enc(x) for x in t) + bytes([NIL_EXT]))
    if isinstance(t, (bytes, bytearray)):
        return bytes([BINARY_EXT]) + struct.pack(">I", len(t)) + bytes(t)
    if isinstance(t, str):
        # plain str -> binary (the bridge's convention for text)
        return _enc(t.encode("utf-8"))
    if isinstance(t, dict):
        out = bytes([MAP_EXT]) + struct.pack(">I", len(t))
        for k, v in t.items():
            out += _enc(k) + _enc(v)
        return out
    raise TypeError(f"cannot encode {type(t).__name__}: {t!r}")


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------

def decode(data: bytes):
    """binary_to_term/1.  Returns the term; raises on trailing bytes."""
    if not data or data[0] != VERSION:
        raise ValueError("bad ETF version byte")
    term, rest = _dec(memoryview(data)[1:])
    if len(rest):
        raise ValueError(f"{len(rest)} trailing bytes after term")
    return term


def _dec(b: memoryview):
    tag = b[0]
    b = b[1:]
    if tag == SMALL_INTEGER_EXT:
        return b[0], b[1:]
    if tag == INTEGER_EXT:
        return struct.unpack(">i", b[:4])[0], b[4:]
    if tag == FLOAT_NEW_EXT:
        return struct.unpack(">d", b[:8])[0], b[8:]
    if tag == SMALL_ATOM_UTF8_EXT:
        n = b[0]
        return _atom(bytes(b[1:1 + n])), b[1 + n:]
    if tag == ATOM_UTF8_EXT:
        n = struct.unpack(">H", b[:2])[0]
        return _atom(bytes(b[2:2 + n])), b[2 + n:]
    if tag in (SMALL_TUPLE_EXT, LARGE_TUPLE_EXT):
        if tag == SMALL_TUPLE_EXT:
            n, b = b[0], b[1:]
        else:
            n, b = struct.unpack(">I", b[:4])[0], b[4:]
        items = []
        for _ in range(n):
            x, b = _dec(b)
            items.append(x)
        return tuple(items), b
    if tag == NIL_EXT:
        return [], b
    if tag == STRING_EXT:  # list of small ints packed as bytes
        n = struct.unpack(">H", b[:2])[0]
        return list(b[2:2 + n]), b[2 + n:]
    if tag == LIST_EXT:
        n = struct.unpack(">I", b[:4])[0]
        b = b[4:]
        items = []
        for _ in range(n):
            x, b = _dec(b)
            items.append(x)
        tail, b = _dec(b)
        if tail != []:
            raise ValueError("improper lists unsupported")
        return items, b
    if tag == BINARY_EXT:
        n = struct.unpack(">I", b[:4])[0]
        return bytes(b[4:4 + n]), b[4 + n:]
    if tag == SMALL_BIG_EXT:
        n, sign = b[0], b[1]
        mag = 0
        for i, d in enumerate(bytes(b[2:2 + n])):
            mag |= d << (8 * i)
        return (-mag if sign else mag), b[2 + n:]
    if tag == MAP_EXT:
        n = struct.unpack(">I", b[:4])[0]
        b = b[4:]
        out = {}
        for _ in range(n):
            k, b = _dec(b)
            v, b = _dec(b)
            out[k] = v
        return out, b
    raise ValueError(f"unsupported ETF tag {tag}")


def _atom(raw: bytes):
    s = raw.decode("utf-8")
    if s == "true":
        return True
    if s == "false":
        return False
    return Atom(s)


# ---------------------------------------------------------------------------
# {packet, 4} framing (partisan_peer_socket's framing; also standard
# open_port({packet, 4}) framing on the Erlang side)
# ---------------------------------------------------------------------------

def frame(term) -> bytes:
    payload = encode(term)
    return struct.pack(">I", len(payload)) + payload


def read_frame(stream):
    """Read one framed term from a binary stream; None at EOF."""
    head = stream.read(4)
    if not head or len(head) < 4:
        return None
    (n,) = struct.unpack(">I", head)
    payload = stream.read(n)
    if len(payload) < n:
        raise EOFError("truncated frame")
    return decode(payload)
