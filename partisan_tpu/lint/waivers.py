"""The pinned waiver baseline: documented exceptions to the rule
catalog.  Every entry maps an exact finding fingerprint
(``rule:file:function:detail`` — no line numbers, stable across edits)
to the REASON the exception is sound.  Anything the rules flag that is
not pinned here fails the lint gate; in full-matrix runs a pinned entry
that no finding matched fails too (stale waiver — the exception it
documented no longer exists, delete it).

Protocol for adding one: reproduce the finding with ``python
tools/jaxlint.py``, convince yourself the flagged site is actually
bounded/deterministic (write the argument down — the value here IS the
review artifact), and pin the printed fingerprint.  Prefer fixing the
site (clip-then-narrow, unique_indices=True) over waiving it.
"""

WAIVERS: dict[str, str] = {
    # provenance.stamp writes the sender tree hop into the int16 hop
    # plane (types.NARROW_WIRE_DTYPES).  The value read off the model's
    # hop word is int32 as far as the analyzer can see, but the depth
    # is documented-bounded: the claim accumulator clamps to
    # 2^(30 - gid_bits) (~2^13 at 100k nodes) and a plumtree hop grows
    # by at most 1 per relay round — far under 2^15 at any horizon the
    # scan can reach.  See the dtype-range table in types.py.
    "narrow-dtype-overflow:partisan_tpu/provenance.py:stamp:"
    "convert_element_type@int16":
        "prov_hop is depth-bounded (claim clamp 2^(30-bits), +1/round) "
        "— int16 per types.NARROW_WIRE_DTYPES",
    # health.py's FastSV component counter: pointer-jumping min-label
    # propagation scatters `.at[...].min(...)` repeatedly into the same
    # label table.  min is commutative and associative, so overlapping
    # updates commute — the chain is deterministic by construction
    # (gated against the host BFS oracle in tests/test_health.py).
    "scatter-overlap:partisan_tpu/health.py:body:"
    "chain:scatter-min@<unscoped>":
        "FastSV min-label propagation: min-scatter chains commute; "
        "BFS-oracle-gated in tests/test_health.py",
}
