"""Pluggable plumtree broadcast-handler behaviour
(partisan_plumtree_broadcast_handler.erl:47-78).

The reference lets applications supply broadcast_data/merge/is_stale/
graft/exchange; these tests drive application-defined payload semantics
through the SAME epidemic tree the default version handler uses:

- a G-counter CRDT handler (merge = per-actor max) converging across the
  overlay, including concurrent increments from different actors merging
  commutatively,
- a last-writer-wins register handler whose join is NOT a per-word max
  (the value rides with the winning timestamp — exercises the general
  join path, with exchange ignored like the reference's default backend,
  partisan_plumtree_backend.erl:22-35),
- the exchange start cap (broadcast_start_exchange_limit,
  partisan_config.erl:750-755).
"""

import pytest

from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config, PlumtreeConfig
from partisan_tpu.models.handlers import (
    GCounterHandler, LWWHandler, VersionHandler)
from partisan_tpu.models.plumtree import Plumtree

N = 12


def _boot(model, n=N, **kw) -> tuple[Cluster, object, Config]:
    cfg = Config(n_nodes=n, seed=3, peer_service_manager="hyparview",
                 msg_words=16, **kw)
    cl = Cluster(cfg, model=model)
    st = cl.init()
    for node in range(1, n):
        st = st._replace(manager=cl.manager.join(cfg, st.manager, node,
                                                 target=0))
    st = cl.steps(st, 12)
    return cl, st, cfg


def test_gcounter_handler_broadcasts_through_tree():
    """A CRDT payload (G-counter) rides the same eager/lazy tree."""
    model = Plumtree(handler=GCounterHandler(n_actors=4))
    cl, st, cfg = _boot(model)
    # actor 2 increments to 5 at node 3
    st = st._replace(model=model.broadcast(st.model, 3, 0, {2: 5}))
    st, r = cl.run_until(
        st, lambda s: float(model.coverage(
            s.model, s.faults.alive, 0, {2: 5})) == 1.0, max_rounds=60)
    assert r != -1, "g-counter broadcast did not converge"
    assert int(model.handler.total(st.model.data[7, 0])) == 5


def test_gcounter_concurrent_increments_merge():
    """Concurrent increments from different actors merge commutatively
    (merge/2 is the CRDT join, not last-write-wins)."""
    model = Plumtree(handler=GCounterHandler(n_actors=4))
    cl, st, cfg = _boot(model)
    st = st._replace(model=model.broadcast(st.model, 3, 0, {0: 2}))
    st = st._replace(model=model.broadcast(st.model, 8, 0, {1: 3}))
    target = {0: 2, 1: 3}
    st, r = cl.run_until(
        st, lambda s: float(model.coverage(
            s.model, s.faults.alive, 0, target)) == 1.0, max_rounds=80)
    assert r != -1, "concurrent g-counter increments did not converge"
    assert int(model.handler.total(st.model.data[0, 0])) == 5


def test_lww_handler_general_join():
    """LWW register: join is by timestamp order, not per-word max — a
    LOWER value with a HIGHER timestamp must win everywhere."""
    model = Plumtree(handler=LWWHandler())
    cl, st, cfg = _boot(model)
    st = st._replace(model=model.broadcast(st.model, 2, 0, (10, 90)))
    st, r = cl.run_until(
        st, lambda s: float(model.coverage(
            s.model, s.faults.alive, 0, (10, 90))) == 1.0, max_rounds=60)
    assert r != -1
    # newer timestamp, smaller value: must supersede (ts=20, v=7)
    st = st._replace(model=model.broadcast(st.model, 5, 0, (20, 7)))
    st, r = cl.run_until(
        st, lambda s: float(model.coverage(
            s.model, s.faults.alive, 0, (20, 7))) == 1.0, max_rounds=60)
    assert r != -1, "LWW overwrite did not converge"
    assert st.model.data[9, 0].tolist() == [20, 7]


def test_lww_stale_update_ignored():
    model = Plumtree(handler=LWWHandler())
    cl, st, cfg = _boot(model)
    st = st._replace(model=model.broadcast(st.model, 2, 0, (50, 1)))
    st, r = cl.run_until(
        st, lambda s: float(model.coverage(
            s.model, s.faults.alive, 0, (50, 1))) == 1.0, max_rounds=60)
    assert r != -1
    # an OLDER timestamp is stale at injection (join keeps the winner)
    st = st._replace(model=model.broadcast(st.model, 4, 0, (40, 99)))
    assert st.model.data[4, 0].tolist() == [50, 1]


def test_version_handler_unchanged_default():
    """Plumtree() without a handler is the version semantics (the default
    partisan_plumtree_backend), including int broadcast/coverage args."""
    model = Plumtree()
    assert isinstance(model.handler, VersionHandler)
    cl, st, cfg = _boot(model)
    st = st._replace(model=model.broadcast(st.model, 0, 0, 7))
    st, r = cl.run_until(
        st, lambda s: float(model.coverage(
            s.model, s.faults.alive, 0, 7)) == 1.0, max_rounds=60)
    assert r != -1


def test_exchange_limit_zero_disables_aae():
    """With exchange_limit=0 the periodic AAE walk is off (parity with
    the reference's default backend, whose exchange is ignore) — the
    connect-time handshake still fires on NEW links, and the payload
    converges via the tree."""
    model = Plumtree(handler=GCounterHandler(n_actors=2))
    cl, st, cfg = _boot(
        model, plumtree=PlumtreeConfig(exchange_limit=0))
    st = st._replace(model=model.broadcast(st.model, 1, 0, {0: 4}))
    st, r = cl.run_until(
        st, lambda s: float(model.coverage(
            s.model, s.faults.alive, 0, {0: 4})) == 1.0, max_rounds=80)
    assert r != -1, "tree-only (no AAE) convergence failed"


def test_payload_width_validation():
    with pytest.raises(ValueError, match="msg_words"):
        # 8-word handler payload cannot fit msg_words=12
        Config(n_nodes=4, msg_words=12).n_nodes  # config itself is fine
        model = Plumtree(handler=GCounterHandler(n_actors=8))
        Cluster(Config(n_nodes=4, msg_words=12,
                       peer_service_manager="hyparview"),
                model=model).init()
