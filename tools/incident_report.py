"""Incident observatory report (the ``BENCH_*.json`` idiom: one
self-describing JSON object per line).

Loads an ops-journal JSON-lines artifact (``opslog.Journal.to_jsonl``
— what ``scenarios.py --ops`` and ``soak_report.py`` commit), matches
the incident-span catalog over it (``opslog.match``: every injected
fault paired with its detection, reaction, and recovery, with measured
round-latencies for each leg), accounts the per-channel SLO error
budgets (``opslog.error_budgets``), and prints::

    {"kind": "ops_span",     ...}   one per matched incident
    {"kind": "ops_orphan",   ...}   reactions no span claimed
    {"kind": "ops_watchdog", ...}   in-scan invariant breach state
                                    (when the journal carries the
                                    watchdog stream: armed, breach
                                    count, exact first breach round,
                                    trip state)
    {"kind": "ops_budget",   ...}   one per polled channel
    {"kind": "ops_gate",     ...}   the verdict (always printed)
    {"kind": "summary",      ...}   last line, always

Usage::

    python tools/incident_report.py JOURNAL [--gate] [--slo-rounds N]
        [--budget-frac F] [--exempt CH1,CH2] [--crowd-x1000 N]
        [--spool SPOOL]

``--gate`` makes the exit status the verdict: nonzero when any
observable incident stayed open or undetected, or a non-exempt
channel's error budget exhausted (``opslog.gate``) — the scenario/CI
gate for committed soak artifacts.  Budgets need ``--slo-rounds``
(the journal's chunk entries must carry windowed p99 polls,
``SoakConfig.poll_latency``); without it only spans gate.

``--spool SPOOL`` merges a full-horizon telemetry spool
(``opslog.ingest_spool``) into the journal before matching: plane
coverage extends back to the spool's start, so ring-expired incidents
judge as real closed/undetected spans instead of "unobservable" —
the re-judge path for committed ``OPS_*.spool.jsonl`` artifacts.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

USAGE = ("usage: incident_report.py JOURNAL [--gate] [--slo-rounds N] "
         "[--budget-frac F] [--exempt CH1,CH2] [--crowd-x1000 N] "
         "[--spool SPOOL]")


def main() -> None:
    if "--help" in sys.argv or "-h" in sys.argv:
        print(USAGE)
        print(__doc__.strip())
        return
    VALUE_FLAGS = ("--slo-rounds", "--budget-frac", "--exempt",
                   "--crowd-x1000", "--spool")
    argv = sys.argv[1:]
    args, opts, do_gate = [], {}, False
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in VALUE_FLAGS:
            if i + 1 >= len(argv):
                raise SystemExit(f"{a} needs a value\n{USAGE}")
            opts[a] = argv[i + 1]
            i += 2
        elif a == "--gate":
            do_gate = True
            i += 1
        elif a.startswith("--"):
            raise SystemExit(f"unknown flag {a}\n{USAGE}")
        else:
            args.append(a)
            i += 1
    if len(args) != 1:
        raise SystemExit(USAGE)
    path = args[0]
    if not os.path.exists(path):
        raise SystemExit(f"no such journal: {path}")

    from partisan_tpu import opslog

    journal = opslog.Journal.from_jsonl(path)
    crowd = opts.get("--crowd-x1000")
    spool_path = opts.get("--spool")
    if spool_path is not None:
        if not os.path.exists(spool_path):
            raise SystemExit(f"no such spool: {spool_path}")
        slo_opt = opts.get("--slo-rounds")
        journal = opslog.ingest_spool(
            spool_path, journal=journal,
            slo_rounds=int(slo_opt) if slo_opt else None,
            crowd_x1000=int(crowd) if crowd else None)
    matched = opslog.match(
        journal, crowd_x1000=int(crowd) if crowd else None)
    for span in matched["spans"]:
        print(json.dumps(span))
    for orphan in matched["orphans"]:
        print(json.dumps(orphan))
    if "watchdog" in journal.streams:
        print(json.dumps({"kind": "ops_watchdog",
                          **opslog.watchdog_summary(journal)}))
    budgets = None
    slo = opts.get("--slo-rounds")
    if slo is not None:
        budgets = opslog.error_budgets(
            journal, slo_rounds=int(slo),
            budget_frac=float(opts.get("--budget-frac", 0.25)))
        for row in budgets:
            print(json.dumps(row))
    exempt = tuple(c for c in opts.get("--exempt", "").split(",") if c)
    verdict = opslog.gate(matched, budgets, exempt=exempt)
    print(json.dumps(verdict))
    lo, hi = journal.span_window()
    print(json.dumps({"kind": "summary", "entries": len(journal.entries),
                      "start": lo, "end": hi,
                      "streams": sorted(journal.streams),
                      **matched["counts"], "ok": verdict["ok"]}))
    if do_gate and not verdict["ok"]:
        raise SystemExit(2)


if __name__ == "__main__":
    main()
