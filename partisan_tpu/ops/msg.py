"""Building fixed-width message records (see types.py for the layout).

Layout: with ``Config.plane_major`` (the default) a freshly built stack
is a :class:`partisan_tpu.ops.plane.Planes` struct — one ``[...,]``
tensor per wire word, each stored at its narrowest documented dtype
(types.NARROW_WIRE_DTYPES) — and NO minor-axis interleave happens here
at all.  BENCH_NOTES' corrected cost model measured ``build``'s
plane-interleave alone at ~25% of the 32k round (~14 calls × ~4.7 ms on
the TPU relay); the plane-major pipeline defers the interleave to the
single wire boundary in ``cluster.round_body`` (or eliminates it where
the exchange ships planes).  Callers pass the ``Config`` as the first
argument; passing a bare ``msg_words`` int keeps the legacy interleaved
int32 stack (the A/B baseline and the layout the bit-parity tests pin).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import Array

from partisan_tpu import types as T
from partisan_tpu.ops import plane as plane_ops


def _layout(cfg_or_words) -> tuple[int, bool]:
    """(msg_words, plane_major) from a Config or a bare word count."""
    if isinstance(cfg_or_words, int):
        return cfg_or_words, False
    return cfg_or_words.msg_words, cfg_or_words.plane_major


def build(cfg_or_words, kind: Array | int, src: Array, dst: Array, *,
          channel: Array | int = 0, ttl: Array | int = 0,
          clock: Array | int = 0, lane: Array | int = 0,
          flags: Array | int = 0, payload: tuple = ()):
    """Build message records of shape broadcast(src, dst, ...) + [msg_words].

    A record whose ``dst`` is negative is marked empty (kind NONE) so
    callers can pass -1 destinations from unused sampling slots directly.

    ``cfg_or_words``: the ``Config`` (preferred — selects the layout per
    ``cfg.plane_major``) or a bare ``msg_words`` int (legacy interleaved
    int32 stack).  Plane-major output is a :class:`plane.Planes`; the
    word values are identical either way (narrow planes widen back to
    the same int32 at the wire boundary).
    """
    msg_words, planes = _layout(cfg_or_words)
    shape = jnp.broadcast_shapes(
        jnp.shape(kind), jnp.shape(src), jnp.shape(dst),
        jnp.shape(channel), jnp.shape(ttl), jnp.shape(clock),
        jnp.shape(lane), jnp.shape(flags),
        *(jnp.shape(p) for p in payload),
    )
    dst = jnp.broadcast_to(jnp.asarray(dst, jnp.int32), shape)
    valid = dst >= 0
    if msg_words < T.HDR_WORDS:
        raise ValueError(
            f"msg_words={msg_words} < header width {T.HDR_WORDS}")
    if len(payload) > msg_words - T.HDR_WORDS:
        raise ValueError(
            f"{len(payload)} payload words exceed msg_words={msg_words}")

    def w(x, i):
        dt = T.wire_dtype(i) if planes else jnp.int32
        return jnp.broadcast_to(jnp.asarray(x).astype(dt), shape)

    words = [jnp.where(valid, w(kind, T.W_KIND), 0), w(src, T.W_SRC),
             jnp.where(valid, dst, 0), w(channel, T.W_CHANNEL),
             w(ttl, T.W_TTL), w(clock, T.W_CLOCK),
             w(lane, T.W_LANE), w(flags, T.W_FLAGS)]
    words += [w(p, T.HDR_WORDS + i) for i, p in enumerate(payload)]
    words += [jnp.zeros(shape, T.wire_dtype(i) if planes else jnp.int32)
              for i in range(len(words), msg_words)]
    if planes:
        return plane_ops.Planes(words)
    # Legacy layout: assembled as ONE stack of word planes (the previous
    # zeros-then-12-sequential-.at[].set form cost ~4.7 ms per call at
    # 32k x 16 slots on the TPU relay — BENCH_NOTES "corrected cost
    # model").
    return jnp.stack(words, axis=-1)


def zero_stack(cfg_or_words, shape: tuple):
    """An all-empty ``msg_words``-wide emission block of record shape
    ``shape`` (no word axis) — the layout-aware successor of
    ``jnp.zeros(shape + (msg_words,), jnp.int32)`` used for quiet
    lax.cond branches and fixed-width padding blocks."""
    msg_words, planes = _layout(cfg_or_words)
    if planes:
        return plane_ops.zero_planes(
            tuple(shape), tuple(T.wire_dtype(i) for i in range(msg_words)))
    return jnp.zeros(tuple(shape) + (msg_words,), jnp.int32)


def zero_wire(cfg, shape: tuple):
    """An all-empty ``wire_words``-wide record block (trailing
    provenance/latency words included) — for mid-round control-message
    builders (acks, resets) and queued-copy buffers, which hold
    FULL-width records."""
    if cfg.plane_major:
        return plane_ops.zero_planes(tuple(shape), cfg.wire_dtypes)
    return jnp.zeros(tuple(shape) + (cfg.wire_words,), jnp.int32)


def is_kind(msgs, kind: int) -> Array:
    """bool mask over [..., W] records (either layout)."""
    return msgs[..., T.W_KIND] == kind
