"""partisan_gen_event: the event-manager loop (reference
priv/otp/24/partisan_gen_event.erl, 1014 LoC).

One :class:`GenEvent` process owns an ordered list of installed
handlers (test/partisan_gen_event_SUITE.erl semantics):

- handlers receive events in ADD order, each with independent state,
- ``notify`` is fire-and-forget; ``sync_notify`` replies only after
  every handler ran,
- ``call`` targets ONE handler by id and returns its reply,
- ``delete_handler`` stops delivery to that handler and returns its
  final state (the terminate/2 result),
- a handler that crashes on an event is removed silently; the remaining
  handlers keep running (gen_event isolation),
- ``swap_handler`` atomically replaces a handler, seeding the new one
  with the old one's state.

Client side: :class:`Notifier`.
"""

from __future__ import annotations

from typing import Optional

from partisan_tpu.otp import gen


class Handler:
    """One installed handler: integer state plus an event log.  Override
    :meth:`handle` for custom behavior; raising removes the handler."""

    def __init__(self, hid: int, state: int = 0) -> None:
        self.id = hid
        self.state = state
        self.events: list[int] = []

    def handle(self, ev: int, arg: int) -> None:
        self.state += arg
        self.events.append(arg)


class GenEvent(gen.Proc):
    """The event-manager process."""

    def __init__(self, port: gen.Port) -> None:
        super().__init__(port)
        self.handlers: list[Handler] = []

    # -- handler management (gen_event:add_handler etc.) ---------------
    def add_handler(self, handler: Handler) -> None:
        self.handlers.append(handler)

    def delete_handler(self, hid: int) -> Optional[int]:
        for h in list(self.handlers):
            if h.id == hid:
                self.handlers.remove(h)
                return h.state          # terminate/2 returns the state
        return None

    def swap_handler(self, old_hid: int, new_handler_cls, new_hid: int
                     ) -> bool:
        """The new handler is seeded with the old one's terminate result
        (OTP swap semantics), atomically in place."""
        for i, h in enumerate(self.handlers):
            if h.id == old_hid:
                self.handlers[i] = new_handler_cls(new_hid, h.state)
                return True
        return False

    # -- the manager loop ----------------------------------------------
    def process(self, _rnd: int = 0) -> None:
        for src, words in self.drain():
            op, mref, ev, arg = words[0], words[1], words[2], words[3]
            if op in (gen.OP_NOTIFY, gen.OP_SYNC_NOTIFY):
                for h in list(self.handlers):
                    try:
                        h.handle(ev, arg)
                    except Exception:
                        # a crashing handler is removed; others continue
                        self.handlers.remove(h)
                if op == gen.OP_SYNC_NOTIFY:
                    gen.reply(self, src, mref, True, 0)
            elif op == gen.OP_CALL:
                # call/2: ev carries the TARGET handler id
                for h in self.handlers:
                    if h.id == ev:
                        gen.reply(self, src, mref, True, h.state)
                        break
                else:
                    gen.reply(self, src, mref, False, 0)


class Notifier(gen.Caller):
    """Client API: notify / sync_notify / call against a manager."""

    def notify(self, mgr_id: int, ev: int, arg: int) -> None:
        self.forward(mgr_id, [gen.OP_NOTIFY, 0, ev, arg])

    def sync_notify(self, mgr: GenEvent, ev: int, arg: int,
                    timeout_steps: int = 12):
        return self.call(mgr.id, ev, arg, pump=mgr.process,
                         timeout_steps=timeout_steps,
                         op=gen.OP_SYNC_NOTIFY)

    def call_handler(self, mgr: GenEvent, hid: int,
                     timeout_steps: int = 12):
        return self.call(mgr.id, hid, 0, pump=mgr.process,
                         timeout_steps=timeout_steps)
