"""Shared test fixtures — the multi-node-without-a-cluster fixture
analogue (reference test/partisan_support.erl:46+): config factories,
staggered bootstrap, and host-side overlay graph checks."""

import collections

from partisan_tpu.config import Config


def hv_config(n, seed, **kw):
    kw.setdefault("msg_words", 16)
    return Config(n_nodes=n, seed=seed, peer_service_manager="hyparview",
                  **kw)


def fm_config(n, seed, **kw):
    kw.setdefault("inbox_cap", max(32, n + 8))
    return Config(n_nodes=n, seed=seed, **kw)


def boot_fullmesh(cl, contact=0, settle=15):
    """All nodes join via the contact, then membership gossip settles."""
    st = cl.init()
    m = st.manager
    for i in range(cl.cfg.n_nodes):
        if i != contact:
            m = cl.manager.join(cl.cfg, m, i, contact)
    st = st._replace(manager=m)
    return cl.steps(st, settle)


def staggered_join(cl, st, contact=0):
    """Each node joins via the contact, a few per round (the reference
    suite boots nodes one at a time, partisan_support.erl:46+)."""
    cfg = cl.cfg
    for base in range(1, cfg.n_nodes, 4):
        m = st.manager
        for i in range(base, min(base + 4, cfg.n_nodes)):
            m = cl.manager.join(cfg, m, i, contact)
        st = st._replace(manager=m)
        st = cl.steps(st, 2)
    return st


def boot_hyparview(cl, settle=40):
    return cl.steps(staggered_join(cl, cl.init()), settle)


def components(active, alive):
    """Connected components of the overlay (undirected union of active
    views), host-side."""
    n = active.shape[0]
    adj = collections.defaultdict(set)
    for i in range(n):
        if not alive[i]:
            continue
        for j in active[i]:
            j = int(j)
            if j >= 0 and alive[j]:
                adj[i].add(j)
                adj[j].add(i)
    seen, comps = set(), []
    for s in range(n):
        if not alive[s] or s in seen:
            continue
        comp, stack = set(), [s]
        while stack:
            x = stack.pop()
            if x in comp:
                continue
            comp.add(x)
            stack.extend(adj[x] - comp)
        seen |= comp
        comps.append(comp)
    return comps
