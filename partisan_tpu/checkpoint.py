"""Checkpoint / resume of cluster state (SURVEY.md §5.4).

The reference persists its critical state continuously: the membership
CRDT to ``<data_dir>/default_peer_service/cluster_state`` on every
mutation (partisan_full_membership_strategy.erl:289-330), the causality
backend's clock/order-buffer via ``write_state``
(partisan_causality_backend.erl:218, :243), and test traces via dets
(partisan_trace_file.erl).

The sim's entire cluster lives in one ``ClusterState`` pytree, so a
checkpoint is a snapshot of its leaves (the "jax checkpointing of the
cluster-state tensors" the survey prescribes).  Restore rebuilds the
pytree against a structural template — typically ``cluster.init()`` —
which also revalidates that the checkpoint matches the configuration.

Format: one ``.npz`` per checkpoint (leaf arrays + round number), plus
``latest``-by-round discovery over a directory, supporting the
crash/restart cycle the reference's re-join path exercises
(partisan_full_membership_strategy.erl load-from-disk at init).

Crash-safety hardening (the soak engine's contract, soak.py):

- **atomic writes** — every save lands in a same-directory temp file
  first and is published with ``os.replace``, so a writer killed
  mid-checkpoint can never leave a half-written ``.npz`` under the
  canonical name (the reference's dets files get the same guarantee
  from dets repair; an interrupted sim save must not poison the resume
  path the minute-mark fault relies on, tools/MINUTE_FAULT.md),
- **config fingerprint** — ``save(..., cfg=...)`` stores a digest of
  the full Config (including the wire-word layout and storage dtypes,
  which PR 6 made config-dependent) so a restore against a drifted
  configuration fails loudly even when the leaf shapes happen to agree,
- **round validation** — the state's round counter is stored beside the
  leaves; ``restore`` cross-checks it against the restored ``rnd`` leaf
  and (optionally) a caller-expected round,
- **corruption detection** — a truncated or bit-flipped file raises
  :class:`CheckpointError` with a clear message instead of a bare
  zipfile/zlib traceback (numpy's zip container CRC-checks each member;
  we surface those failures and the missing-member case uniformly).
"""

from __future__ import annotations

import hashlib
import os
import re
import tempfile
import zipfile
import zlib

import jax
import numpy as np

# Version 2 adds the fingerprint/round/wire-layout metadata; version 1
# files (leaves only) remain restorable — their extra validation is
# simply unavailable.
FORMAT_VERSION = 2
_COMPAT_VERSIONS = (1, 2)
_NAME = re.compile(r"^ckpt_(\d+)\.npz$")


class CheckpointError(ValueError):
    """A checkpoint could not be restored: corrupt/truncated file,
    configuration drift, or a round/template mismatch."""


class CheckpointCorruptError(CheckpointError):
    """The file itself is damaged (torn write, bit flip, not a zip).
    Distinct from drift/mismatch because ``restore_latest`` may fall
    back to an OLDER intact checkpoint on corruption — but never
    across config drift (older files would mask the real problem)."""


def config_fingerprint(cfg) -> str:
    """Stable digest of a Config — including the resolved wire layout
    (word count + per-word storage dtypes), which determines every wire
    buffer's shape and dtype.  Two configs with equal fingerprints
    produce structurally interchangeable states; a mismatch means the
    checkpoint was written under a different configuration and must not
    be silently restored (the drift ``restore``'s shape check alone can
    miss: e.g. a seed or cadence change keeps all shapes)."""
    wire = cfg.wire_layout
    if isinstance(wire, tuple):
        wire_desc = ",".join(str(np.dtype(d)) for d in wire)
    else:
        wire_desc = f"int32x{wire}"
    blob = f"{cfg!r}|wire={wire_desc}".encode()
    return hashlib.sha256(blob).hexdigest()


def save(state, path: str | os.PathLike, cfg=None) -> None:
    """Snapshot a state pytree to ``path`` (.npz), atomically.

    The write goes to a same-directory temp file and is published with
    ``os.replace``, so a crash mid-write never leaves a torn file at
    ``path``.  Pass ``cfg`` to stamp the checkpoint with the config
    fingerprint (validated by ``restore`` when it, too, is given the
    config)."""
    path = os.fspath(path)
    leaves = jax.tree.leaves(state)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    meta = {"version": FORMAT_VERSION, "n_leaves": len(leaves)}
    rnd = getattr(state, "rnd", None)
    if rnd is not None:
        meta["rnd"] = np.int64(int(np.asarray(rnd)))
    if cfg is not None:
        meta["fingerprint"] = np.str_(config_fingerprint(cfg))
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".tmp.",
        dir=os.path.dirname(path) or ".")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **meta, **arrays)
            # Flush to stable storage BEFORE publishing: os.replace is
            # atomic in the namespace, but an OS crash could otherwise
            # still publish a name pointing at torn contents.
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _open_checked(path):
    """np.load with the corruption cases mapped to CheckpointError."""
    try:
        return np.load(path)
    except (OSError, ValueError, zipfile.BadZipFile, zlib.error) as e:
        raise CheckpointCorruptError(
            f"checkpoint {path!r} is corrupt or truncated: {e}") from e


def restore(path: str | os.PathLike, like, cfg=None,
            expect_rnd: int | None = None):
    """Rebuild a checkpoint against the structural template ``like``
    (same treedef — e.g. ``cluster.init()``).  Shape/dtype mismatches
    raise, catching config drift between save and restore; ``cfg``
    additionally validates the stored config fingerprint, and
    ``expect_rnd`` the stored round number.  Corrupt or truncated files
    raise :class:`CheckpointError` (reading decompresses every member,
    so a torn tail or bit flip surfaces here, not later)."""
    import jax.numpy as jnp

    path = os.fspath(path)
    treedef = jax.tree.structure(like)
    tmpl = jax.tree.leaves(like)
    with _open_checked(path) as z:
        if "version" not in z.files:
            raise CheckpointError(
                f"checkpoint {path!r} has no version field "
                "(not a partisan_tpu checkpoint?)")
        # Metadata members decompress on read: a bit flip confined to
        # one of them must still surface as CheckpointError, not a raw
        # zlib/zip traceback.
        try:
            version = int(z["version"])
            stored_fp = (str(z["fingerprint"])
                         if "fingerprint" in z.files else None)
            n = int(z["n_leaves"])
            stored_rnd = int(z["rnd"]) if "rnd" in z.files else None
        except (KeyError, OSError, ValueError, zipfile.BadZipFile,
                zlib.error) as e:
            raise CheckpointCorruptError(
                f"checkpoint {path!r} is corrupt or truncated in its "
                f"metadata: {e}") from e
        if version not in _COMPAT_VERSIONS:
            raise CheckpointError(
                f"checkpoint version {version} not supported "
                f"(expected one of {_COMPAT_VERSIONS})")
        if cfg is not None and stored_fp is not None:
            want = config_fingerprint(cfg)
            if stored_fp != want:
                raise CheckpointError(
                    f"checkpoint {path!r} was written under a different "
                    f"configuration (fingerprint {stored_fp[:12]}… != "
                    f"{want[:12]}…) — refusing to restore across config "
                    "drift")
        if n != len(tmpl):
            raise CheckpointError(
                f"checkpoint has {n} leaves, template has {len(tmpl)} "
                f"(configuration changed since save?)")
        leaves = []
        try:
            for i, t in enumerate(tmpl):
                a = z[f"leaf_{i}"]
                if a.shape != np.shape(t) or a.dtype != np.asarray(t).dtype:
                    raise CheckpointError(
                        f"leaf {i}: checkpoint {a.shape}/{a.dtype} != "
                        f"template {np.shape(t)}/{np.asarray(t).dtype}")
                leaves.append(jnp.asarray(a))
        except (KeyError, OSError, ValueError, zipfile.BadZipFile,
                zlib.error) as e:
            if isinstance(e, CheckpointError):
                raise
            raise CheckpointCorruptError(
                f"checkpoint {path!r} is corrupt or truncated while "
                f"reading leaf {i}: {e}") from e
    out = jax.tree.unflatten(treedef, leaves)
    got_rnd = getattr(out, "rnd", None)
    if got_rnd is not None:
        got = int(np.asarray(got_rnd))
        if stored_rnd is not None and stored_rnd != got:
            raise CheckpointError(
                f"checkpoint {path!r} round metadata {stored_rnd} "
                f"disagrees with its rnd leaf {got} — file corrupt?")
        if expect_rnd is not None and got != int(expect_rnd):
            raise CheckpointError(
                f"checkpoint {path!r} holds round {got}, caller "
                f"expected round {int(expect_rnd)}")
    return out


# ---- step-numbered checkpoint directories ------------------------------

def save_step(state, ckpt_dir: str | os.PathLike, rnd: int,
              cfg=None) -> str:
    """Save as ``<dir>/ckpt_<round>.npz`` (atomic); returns the path."""
    os.makedirs(ckpt_dir, exist_ok=True)
    path = os.path.join(os.fspath(ckpt_dir), f"ckpt_{int(rnd)}.npz")
    save(state, path, cfg=cfg)
    return path


def steps(ckpt_dir: str | os.PathLike) -> list[int]:
    """Rounds with a checkpoint in ``ckpt_dir``, ascending."""
    if not os.path.isdir(ckpt_dir):
        return []
    out = []
    for f in os.listdir(ckpt_dir):
        m = _NAME.match(f)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def restore_latest(ckpt_dir: str | os.PathLike, like, cfg=None):
    """Load the newest INTACT checkpoint, or None if the directory is
    empty — the load-or-bootstrap decision of the reference's init
    (partisan_full_membership_strategy.erl:289-330).

    A corrupt newest file (a torn write published by an OS crash at
    exactly the wrong moment) falls back to the next-older checkpoint
    instead of permanently blocking resume; config drift or a round
    mismatch still raises — every older file would carry the same
    problem, and silently restoring stale pre-drift state would mask
    it."""
    all_steps = steps(ckpt_dir)
    if not all_steps:
        return None
    last_err: CheckpointCorruptError | None = None
    for rnd in reversed(all_steps):
        try:
            return restore(
                os.path.join(os.fspath(ckpt_dir), f"ckpt_{rnd}.npz"),
                like, cfg=cfg, expect_rnd=rnd)
        except CheckpointCorruptError as e:
            last_err = e
    raise CheckpointCorruptError(
        f"every checkpoint in {os.fspath(ckpt_dir)!r} is corrupt "
        f"(newest failure: {last_err})")
