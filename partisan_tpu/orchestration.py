"""Test-orchestration backend (reference
src/partisan_orchestration_backend.erl + the kubernetes/compose
strategies).

Reference behavior: under k8s/docker-compose test rigs, a backend
behaviour exposes ``clients/servers/upload_artifact/download_artifact``
(partisan_orchestration_backend.erl:24-27) with periodic membership
refresh, cluster-graph construction and artifact timers; strategies
discover pods via the k8s API (partisan_kubernetes_orchestration_
strategy.erl:73-90) or compose services.

Sim mapping: orchestration coordinates SCENARIOS — which sim nodes play
client/server roles, and an artifact store for traces/checkpoints the
way the reference ships debug artifacts between nodes.  The kubernetes/
compose strategies' pod-discovery is environment-specific; here a
strategy is anything that yields role sets (a static one is provided —
the compose analogue; a k8s strategy would query its API the same way).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Protocol, Sequence

import numpy as np


class Strategy(Protocol):
    def clients(self) -> Sequence[int]:
        ...

    def servers(self) -> Sequence[int]:
        ...


@dataclasses.dataclass
class StaticStrategy:
    """Fixed role assignment (the compose-file analogue,
    partisan_compose_orchestration_strategy.erl)."""

    client_ids: Sequence[int]
    server_ids: Sequence[int]

    def clients(self) -> Sequence[int]:
        return list(self.client_ids)

    def servers(self) -> Sequence[int]:
        return list(self.server_ids)


@dataclasses.dataclass
class TagStrategy:
    """Role assignment by the client/server tag convention the reference
    uses (tagged node specs, partisan_client_server_peer_service_
    manager.erl:22-43): ids below ``n_servers`` are servers."""

    n_nodes: int
    n_servers: int

    def clients(self) -> Sequence[int]:
        return list(range(self.n_servers, self.n_nodes))

    def servers(self) -> Sequence[int]:
        return list(range(self.n_servers))


@dataclasses.dataclass
class KubernetesStrategy:
    """Pod discovery via the k8s API
    (partisan_kubernetes_orchestration_strategy.erl:73-90: GET
    /api/v1/pods?labelSelector=..., keep Running pods with an IP, read
    the role off the pod labels).

    ``api`` is the injectable pod-list call (in production a k8s client;
    in tests a stub returning pod dicts).  A pod dict mirrors the k8s
    shape: ``{"metadata": {"labels": {...}}, "status": {"phase":
    "Running", "podIP": ...}, "sim_id": int}`` — ``sim_id`` is the
    sim-side node identity (the reference derives node names from pod
    IPs; the simulator's ids are its node names)."""

    api: "Callable[[], Sequence[dict]]"
    selector: tuple[str, str] = ("app", "partisan")
    role_label: str = "tag"

    def _pods(self) -> list[dict]:
        key, val = self.selector
        out = []
        for p in self.api():
            labels = p.get("metadata", {}).get("labels", {})
            status = p.get("status", {})
            if labels.get(key) != val:
                continue             # label selector
            if status.get("phase") != "Running" or not status.get("podIP"):
                continue             # not schedulable yet
            out.append(p)
        return out

    def roles(self) -> tuple[list[int], list[int]]:
        """(clients, servers) from ONE pod-list call — the per-poll
        pattern (the reference lists pods once per refresh timer; two
        separate API calls could read torn cluster snapshots)."""
        pods = self._pods()

        def by(role: str) -> list[int]:
            return sorted(
                int(p["sim_id"]) for p in pods
                if p.get("metadata", {}).get("labels", {})
                    .get(self.role_label) == role)

        return by("client"), by("server")

    def clients(self) -> Sequence[int]:
        return self.roles()[0]

    def servers(self) -> Sequence[int]:
        return self.roles()[1]


@dataclasses.dataclass
class ComposeStrategy:
    """Service discovery for docker-compose rigs
    (partisan_compose_orchestration_strategy.erl): roles come from the
    compose service a container belongs to.  ``services`` is the
    injectable service→containers mapping (compose ps analogue); the
    conventional service names are ``client`` and ``server``."""

    services: "Callable[[], dict[str, Sequence[int]]]"

    def clients(self) -> Sequence[int]:
        return sorted(self.services().get("client", []))

    def servers(self) -> Sequence[int]:
        return sorted(self.services().get("server", []))


@dataclasses.dataclass
class Backend:
    """clients/servers + artifact store + cluster-graph debug view."""

    strategy: Strategy
    artifact_dir: str = "/tmp/partisan_tpu_artifacts"

    def clients(self) -> Sequence[int]:
        return self.strategy.clients()

    def servers(self) -> Sequence[int]:
        return self.strategy.servers()

    # ---- artifacts (upload_artifact/download_artifact) ---------------
    def upload_artifact(self, name: str, data: bytes) -> str:
        os.makedirs(self.artifact_dir, exist_ok=True)
        path = os.path.join(self.artifact_dir, name)
        with open(path, "wb") as f:
            f.write(data)
        return path

    def download_artifact(self, name: str) -> bytes | None:
        path = os.path.join(self.artifact_dir, name)
        if not os.path.exists(path):
            return None
        with open(path, "rb") as f:
            return f.read()

    # ---- cluster graph (debug tree construction, orchestration
    # backend's graph timer) -------------------------------------------
    @staticmethod
    def cluster_graph(cluster, state) -> dict[int, list[int]]:
        """Adjacency (overlay out-edges) as a host dict — the graph the
        reference builds for its debug endpoints."""
        nbrs = np.asarray(cluster.manager.neighbors(
            cluster.cfg, state.manager))
        return {i: [int(d) for d in row if d >= 0]
                for i, row in enumerate(nbrs)}
