"""Delivery semantics: acked delivery with retransmission, and causal
broadcast lanes.

TPU rebuild of two reference backends that wrap the send path:

- **Acked delivery** (partisan_acknowledgement_backend.erl:70-85, driven
  by the pluggable manager: store-on-send :1290-1307, retransmit timer
  :1421-1470, receiver ack + deliver :1835-1881): a message sent with
  ``F_ACK_REQUIRED`` is stored by the sender keyed by its per-sender
  monotonic clock; every ``retransmit_interval`` it is re-sent (flagged
  ``F_RETRANSMISSION``) until the matching ``ACK`` arrives.  Delivery is
  at-least-once — receivers may see duplicates, exactly as in the
  reference fast path.

- **Causal delivery** (partisan_causality_backend.erl: emit stores the
  stamped message for re-emission :172-201, receive buffers until
  dependencies are satisfied :204-220 + :309-344, delivery merges clocks
  :263-300).  The reference's scheme is point-to-point with
  per-destination dependency clocks and a *dominance* check that can be
  satisfied transitively without the dependency being delivered — an
  approximation it acknowledges.  The TPU lane targets the headline
  workload instead (causal **broadcast** at cluster scale, driver config
  #5) and implements exact vector-clock causal broadcast: each logical
  message increments its sender's entry once, every node delivers it at
  most once, in causal order, buffering out-of-order arrivals.  Senders
  must live in the bounded actor space (``gid < cfg.n_actors``); anyone
  receives.  Loss recovery is sender-side: every stamped record enters a
  history ring replayed on the retransmit cadence (the order-buffer-on-
  the-wire analogue, wire format :115), and receivers stale-drop
  already-covered counters, making replay idempotent — app-visible
  delivery is exactly-once, in causal order.

Tensor mapping: a causal record is ``[msg_words + n_actors]`` int32 (the
event words followed by the clock).  Per round, each lane's records from
ALL actors are combined into ONE shared candidate table (an ``lax.psum``
over the shard axis — actors are zero-padded rows off their home shard),
and deliverability for every (node, candidate) pair is evaluated as a
dense vectorized sweep — no per-node scans.  ``CAUSAL_SWEEPS`` sweeps
per round bound in-round chain delivery; longer chains resume next
round, like the reference's redelivery timer (:303-306).

Models opt in per message via flags: ``F_ACK_REQUIRED`` for acked sends;
``F_CAUSAL`` (+ ``W_LANE`` = label index) emits ONE record per logical
broadcast (the destination word is ignored — every node is a receiver;
the sender's own copy is suppressed by the stale-drop since its clock
already covers it).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import Array

from partisan_tpu import faults as faults_mod
from partisan_tpu import latency as latency_mod
from partisan_tpu import provenance as provenance_mod
from partisan_tpu import types as T
from partisan_tpu.config import Config
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.ops import exchange, vclock, views
from partisan_tpu.ops import msg as msg_ops
from partisan_tpu.ops import plane as plane_ops
from partisan_tpu.ops import rng as rng_ops

CAUSAL_SWEEPS = 3     # in-round delivery passes (chain depth per round)
_CAUSAL_SALT = 21     # fault-filter call-site salt for causal lanes
_P2P_EPOCH_TAG = 330  # rank32 tag base for p2p stream epochs
_P2P_REOPEN_TAG = 340  # rank32 tag base for reset-reopened epochs
_P2P_RESET_SLOTS = 4  # pending stream-reset requests per node per lane
_EPOCH_MASK = (1 << 22) - 1  # 22-bit stream epochs (W_LANE bits 8..29:
#                              epoch << 8 must stay inside int32; 22
#                              bits put an accidental old-epoch
#                              collision after a tracking loss at ~2^-22)


class AckState(NamedTuple):
    # Queued-copy invariant ("planes in queues, wire at the boundary"):
    # under Config.plane_major every record buffer below — the ack
    # store, the causal history/arrival rings, the p2p unacked store and
    # future buffer — holds the Planes struct at storage dtypes; queued
    # records are never interleaved or re-widened between emission and
    # the exchange boundary.
    outstanding: Array  # [n_local, ack_cap, W] records — kind==NONE = free slot
    next_clock: Array   # int32[n_local] — next per-sender message clock
    overflow: Array     # int32 — acked sends dropped: store was full


class CausalLane(NamedTuple):
    clock: Array      # uint32[n_local, A] — delivered-state vclock
    buf: Array        # [n_local, B, W+A] records — out-of-order arrivals
    #                   (wide records: W wire words + A clock words; the
    #                   clock words ride as extra int32 planes)
    hist: Array       # [n_local, H, W+A] records — sent-record replay ring
    hist_ptr: Array   # int32[n_local] — ring write position
    overflow: Array   # int32 — records dropped: emit/buffer slots full


class P2PLane(NamedTuple):
    """Point-to-point causal lane (per-destination dependency scheme,
    partisan_causality_backend.erl:204-220): ANY node may send.

    The reference's guarantee is per-(sender → destination) FIFO — each
    message's dependency is the sender's previous send to that same
    destination (the filtered order buffer, :181-190) — with
    opportunistic transitive strengthening via vclock dominance that the
    reference itself documents as approximate.  The tensor encoding
    implements the FIFO contract exactly with per-edge sequence numbers
    and bounded id-keyed bucket tables on both ends (O(n·const) state,
    so it scales to the full cluster — no bounded actor space):

    - sender keeps (dst → seq, epoch) in a ``p2p_dst_cap``-bucket table;
      a bucket collision evicts the old stream, and the NEXT send to the
      evicted destination starts a fresh stream under a new epoch,
    - receiver keeps (src → last-delivered seq, epoch) likewise; an
      unknown or new-epoch stream delivers its first arrival immediately
      (the reference's no-dependency-entry branch, :309-314) and is FIFO
      from there,
    - loss recovery is go-back-N: every sent record holds a slot in a
      bounded UNACKED store replayed on the retransmit cadence until
      the receiver's cumulative stream ack (``P2P_ACK``) covers it; a
      full store DROPS new sends visibly (counted ``overflow``, seq not
      advanced) instead of silently overwriting an unacked record —
      backpressure, never a wedged stream.  Receivers re-ack on
      duplicate arrivals, so a lost ack cannot wedge the store either.

    App-visible delivery is exactly-once per stream in per-edge FIFO
    order.  A tracking reset (bucket collision, ``resets`` counter) ends
    a stream: its unacked records are aborted (``aborted`` counter) and
    the next send opens a fresh epoch — the graceful-degradation
    boundary of the bounded tables (size ``p2p_src_cap`` to the expected
    distinct-sender working set per receiver for exact semantics).
    """

    dst_ids: Array   # int32[n, DC] — sender table: destination ids
    dst_seq: Array   # int32[n, DC] — messages sent to that destination
    dst_ep: Array    # int32[n, DC] — stream epoch
    src_ids: Array   # int32[n, SC] — receiver table: sender ids
    src_seq: Array   # int32[n, SC] — last delivered seq from that sender
    src_ep: Array    # int32[n, SC] — stream epoch
    src_acked: Array  # int32[n, SC] — highest seq cumulatively acked
    reack: Array     # bool[n, SC] — duplicate seen: re-send the ack
    reset_req: Array  # int32[n, R] — senders whose stream arrived
    #                  mid-sequence with no tracking (receiver-side
    #                  eviction): ask them to re-open the stream
    reset_seq: Array  # int32[n, R] — the orphan seq observed (lets the
    #                  sender distinguish true watermark loss from plain
    #                  in-flight reordering and ignore stale requests)
    buf: Array       # int32[n, B, W] — out-of-order arrivals
    hist: Array      # int32[n, H, W] — UNACKED sent records (kind==0
    #                  marks a free slot; freed by P2P_ACK)
    overflow: Array  # int32 — sends dropped (unacked store full /
    #                  emit cap) + future-buffer sheds
    resets: Array    # int32 — bucket evictions (stream tracking resets)
    aborted: Array   # int32 — unacked records dropped because their
    #                  stream reset or their destination crashed


class DeliveryState(NamedTuple):
    ack: AckState | tuple
    lanes: tuple           # one CausalLane per cfg.causal_labels entry
    p2p: tuple             # one P2PLane per cfg.causal_p2p_labels entry
    invalid_causal: Array  # int32 — F_CAUSAL sends dropped (non-actor
                           #   sender or unconfigured lane)


def enabled(cfg: Config) -> bool:
    return cfg.ack_cap > 0 or bool(cfg.causal_labels) \
        or bool(cfg.causal_p2p_labels)


def needs_inbound(cfg: Config) -> bool:
    return bool(cfg.causal_labels) or bool(cfg.causal_p2p_labels)


def _zero_wide(cfg: Config, shape: tuple):
    """All-empty wide causal records (wire words + A clock words): the
    clock block rides as A extra int32 planes under plane_major."""
    if cfg.plane_major:
        return plane_ops.zero_planes(
            tuple(shape), cfg.wire_dtypes + (jnp.int32,) * cfg.n_actors)
    return jnp.zeros(tuple(shape) + (cfg.wire_words + cfg.n_actors,),
                     jnp.int32)


def init(cfg: Config, comm) -> DeliveryState:
    n = comm.n_local
    # wire-width queued copies carry the trailing provenance pair
    # (provenance.py) and birth word (latency.py) verbatim
    ack = AckState(
        outstanding=msg_ops.zero_wire(cfg, (n, cfg.ack_cap)),
        next_clock=jnp.ones((n,), jnp.int32),
        overflow=jnp.int32(0),
    ) if cfg.ack_cap > 0 else ()
    lanes = tuple(
        CausalLane(
            clock=vclock.fresh_matrix(n, cfg.n_actors),
            buf=_zero_wide(cfg, (n, cfg.causal_buf_cap)),
            hist=_zero_wide(cfg, (n, cfg.causal_hist_cap)),
            hist_ptr=jnp.zeros((n,), jnp.int32),
            overflow=jnp.int32(0),
        )
        for _ in cfg.causal_labels
    )
    p2p = tuple(
        P2PLane(
            dst_ids=jnp.full((n, cfg.p2p_dst_cap), -1, jnp.int32),
            dst_seq=jnp.zeros((n, cfg.p2p_dst_cap), jnp.int32),
            dst_ep=jnp.zeros((n, cfg.p2p_dst_cap), jnp.int32),
            src_ids=jnp.full((n, cfg.p2p_src_cap), -1, jnp.int32),
            src_seq=jnp.zeros((n, cfg.p2p_src_cap), jnp.int32),
            src_ep=jnp.zeros((n, cfg.p2p_src_cap), jnp.int32),
            src_acked=jnp.zeros((n, cfg.p2p_src_cap), jnp.int32),
            reack=jnp.zeros((n, cfg.p2p_src_cap), jnp.bool_),
            reset_req=jnp.full((n, _P2P_RESET_SLOTS), -1, jnp.int32),
            reset_seq=jnp.zeros((n, _P2P_RESET_SLOTS), jnp.int32),
            buf=msg_ops.zero_wire(cfg, (n, cfg.p2p_buf_cap)),
            hist=msg_ops.zero_wire(cfg, (n, cfg.p2p_hist_cap)),
            overflow=jnp.int32(0),
            resets=jnp.int32(0),
            aborted=jnp.int32(0),
        )
        for _ in cfg.causal_p2p_labels
    )
    return DeliveryState(ack=ack, lanes=lanes, p2p=p2p,
                         invalid_causal=jnp.int32(0))


def _free_slot_of_rank(free: Array) -> Array:
    """Map send rank -> store slot: ``out[i, r]`` is the index of row
    i's r-th free slot (``S`` = none).  free: bool[n, S]."""
    n, S = free.shape
    free_rank = jnp.cumsum(free, axis=1) - 1
    rows_n = jnp.arange(n)[:, None]
    out = jnp.full((n, S), S, jnp.int32)
    return out.at[
        jnp.broadcast_to(rows_n, free.shape),
        jnp.where(free, free_rank, S)
    ].set(jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None, :],
                           free.shape), mode="drop")


def _compact(recs, mask: Array, cap: int) -> tuple[Array, Array]:
    """Per-node: gather ``recs[i, e]`` where ``mask`` into ``cap`` slots,
    preserving slot order.  Returns (packed [n, cap, w], n_dropped).
    Slot s takes the s-th masked record (ascending slot order — a
    stable sort of the masked indices), fetched by ONE dtype-grouped
    fill-gather instead of the previous per-plane scatter (W scatter
    eqns per call on the causal lanes; the round-cost meter's
    coalescing rule).  Layout-agnostic: arrays ride the same index."""
    n, e = mask.shape
    idxs = jnp.sort(jnp.where(
        mask, jnp.arange(e, dtype=jnp.int32)[None, :], e), axis=1)
    if cap <= e:
        pos = idxs[:, :cap]
    else:   # more slots than sources: the tail stays empty (fill)
        pos = jnp.concatenate(
            [idxs, jnp.full((n, cap - e), e, jnp.int32)], axis=1)
    out = plane_ops.take_rows(recs, pos, fill=True)
    dropped = jnp.sum(jnp.maximum(
        jnp.sum(mask, axis=1) - cap, 0), dtype=jnp.int32)
    return out, dropped


# ---------------------------------------------------------------------------
# Outbound
# ---------------------------------------------------------------------------

def outbound(cfg: Config, comm, st: DeliveryState, emitted: Array,
             ctx: RoundCtx) -> tuple[DeliveryState, Array, tuple]:
    """Process the send path.  Returns (state', emitted', wide_per_lane):
    ack/retransmit records are appended to ``emitted``; causal messages
    are REMOVED from it and returned as per-lane wide-record tensors."""
    gids = comm.local_ids()
    n = emitted.shape[0]
    inb = ctx.inbox.data
    flags_in = inb[..., T.W_FLAGS]
    kind_in = inb[..., T.W_KIND]

    extra = []
    ack = st.ack
    if cfg.ack_cap > 0:
        # 1. Ack everything that arrived flagged (receiver side,
        #    pluggable :1835-1846).  Duplicates re-ack — the reference
        #    acks retransmissions too.
        need_ack = (kind_in != 0) & (flags_in & T.F_ACK_REQUIRED != 0) \
            & ctx.alive[:, None]
        ack_msgs = plane_ops.zeros_like(inb)
        ack_msgs = ack_msgs.at[..., T.W_KIND].set(
            jnp.where(need_ack, T.MsgKind.ACK, 0))
        ack_msgs = ack_msgs.at[..., T.W_SRC].set(
            jnp.where(need_ack, gids[:, None], 0))
        ack_msgs = ack_msgs.at[..., T.W_DST].set(
            jnp.where(need_ack, inb[..., T.W_SRC], 0))
        ack_msgs = ack_msgs.at[..., T.W_CLOCK].set(
            jnp.where(need_ack, inb[..., T.W_CLOCK], 0))
        ack_msgs = provenance_mod.stamp_fresh(cfg, ack_msgs)
        ack_msgs = latency_mod.stamp_fresh(cfg, ack_msgs, ctx.rnd)
        extra.append(ack_msgs)

        # 2. Consume arriving ACKs: clear matching outstanding slots
        #    (match on clock + the acker being the stored destination).
        is_ack = kind_in == T.MsgKind.ACK
        out = ack.outstanding
        hit = (
            (out[..., T.W_CLOCK][:, :, None] == inb[..., T.W_CLOCK][:, None, :])
            & (out[..., T.W_DST][:, :, None] == inb[..., T.W_SRC][:, None, :])
            & is_ack[:, None, :]
            & (out[..., T.W_KIND][:, :, None] != 0)
        ).any(axis=2)
        out = out.at[..., T.W_KIND].set(
            jnp.where(hit, 0, out[..., T.W_KIND]))

        # 3. Stamp + store fresh acked sends (sender side :1290-1307).
        e_flags = emitted[..., T.W_FLAGS]
        fresh = (emitted[..., T.W_KIND] != 0) \
            & (e_flags & T.F_ACK_REQUIRED != 0) \
            & (e_flags & T.F_RETRANSMISSION == 0) \
            & (e_flags & T.F_CAUSAL == 0) \
            & (emitted[..., T.W_KIND] != T.MsgKind.ACK)
        rank = jnp.cumsum(fresh, axis=1) - 1
        clocks = ack.next_clock[:, None] + rank
        emitted = emitted.at[..., T.W_CLOCK].set(
            jnp.where(fresh, clocks, emitted[..., T.W_CLOCK]))

        # Store each fresh send into the k-th free slot of the store,
        # where k is the send's order among this round's fresh sends.
        C = cfg.ack_cap
        free = out[..., T.W_KIND] == 0
        rows_n = jnp.arange(n)[:, None]
        n_free = free.sum(axis=1)
        tgt = jnp.take_along_axis(
            _free_slot_of_rank(free), jnp.clip(rank, 0, C - 1), axis=1)
        store_slot = jnp.where(fresh & (rank < n_free[:, None]), tgt, C)
        out = out.at[
            jnp.broadcast_to(rows_n, store_slot.shape), store_slot
        ].set(emitted, mode="drop")
        # allsum keeps the replicated counter identical across shards.
        overflow = comm.allsum(jnp.sum(
            jnp.maximum(fresh.sum(axis=1) - n_free, 0), dtype=jnp.int32))
        next_clock = ack.next_clock + fresh.sum(axis=1, dtype=jnp.int32)

        # 4. Retransmit tick (pluggable :1421-1470): re-emit the whole
        #    store, flagged.
        refire = ((ctx.rnd + gids) % cfg.retransmit_every == 0) & ctx.alive
        re = out.at[..., T.W_FLAGS].set(
            out[..., T.W_FLAGS] | T.F_RETRANSMISSION)
        re = re.at[..., T.W_KIND].set(
            jnp.where(refire[:, None], out[..., T.W_KIND], 0))
        extra.append(re)

        # Crashed senders freeze their store (their gen_server is dead).
        out = plane_ops.where(ctx.alive[:, None], out, ack.outstanding)
        next_clock = jnp.where(ctx.alive, next_clock, ack.next_clock)
        ack = AckState(outstanding=out, next_clock=next_clock,
                       overflow=ack.overflow + overflow)

    # 5. Causal stamping: pull causal messages off the event lane into
    #    per-lane wide records (emit side, causality_backend :172-201).
    lanes_out = []
    wide_out = []
    for li, lane in enumerate(st.lanes):
        A = cfg.n_actors
        is_c = (emitted[..., T.W_KIND] != 0) \
            & (emitted[..., T.W_FLAGS] & T.F_CAUSAL != 0) \
            & (emitted[..., T.W_LANE] == li)
        # Only actor-resident nodes may send causally.
        actor_ok = (gids < A) & ctx.alive
        is_c = is_c & actor_ok[:, None]

        # The k-th logical message this round gets the clock incremented
        # k+1 times at the sender's own entry.
        n_sent = is_c.sum(axis=1, dtype=vclock.DTYPE)
        rank1 = jnp.cumsum(is_c, axis=1)           # 1-based where is_c
        # Emit-cap overflow drops the TAIL records (slot order), so the
        # clock advances only by the kept prefix — otherwise receivers
        # would wait forever for counters that were never emitted.
        n_kept = jnp.minimum(n_sent, vclock.DTYPE(cfg.causal_emit_cap))
        is_c_all = is_c                       # incl. overflow tail, for
        is_c = is_c & (rank1 <= cfg.causal_emit_cap)  # event-lane removal
        me_actor = jnp.where(gids < A, gids, 0)
        onehot = (jnp.arange(A)[None, :] ==
                  me_actor[:, None]).astype(vclock.DTYPE)
        msg_clocks = lane.clock[:, None, :] + \
            onehot[:, None, :] * rank1[:, :, None].astype(vclock.DTYPE)
        new_clock = lane.clock + onehot * n_kept[:, None]

        wide = plane_ops.append_tail(emitted, msg_clocks)
        packed, _ = _compact(wide, is_c, cfg.causal_emit_cap)
        dropped = jnp.sum(n_sent - n_kept, dtype=jnp.int32)

        # Sender-side loss recovery: history ring + cadenced replay.
        H = cfg.causal_hist_cap
        valid_p = packed[..., T.W_KIND] != 0
        k_idx = jnp.cumsum(valid_p, axis=1) - 1
        pos = jnp.where(valid_p,
                        (lane.hist_ptr[:, None] + k_idx) % H, H)
        rows_n = jnp.broadcast_to(jnp.arange(n)[:, None], pos.shape)
        hist = lane.hist.at[rows_n, pos].set(packed, mode="drop")
        hist_ptr = (lane.hist_ptr
                    + valid_p.sum(axis=1, dtype=jnp.int32)) % H
        refire = ((ctx.rnd + gids) % cfg.retransmit_every == 0) & ctx.alive
        live_slot = refire[:, None] & (hist[..., T.W_KIND] != 0)
        replay = hist.at[..., T.W_FLAGS].set(
            hist[..., T.W_FLAGS] | T.F_RETRANSMISSION)
        # Whole-record zeroing keeps off-actor/idle rows all-zero — the
        # invariant ShardComm.actor_gather's psum reconstruction needs.
        replay = plane_ops.where(live_slot, replay, 0)

        wide_out.append(plane_ops.concat([packed, replay], axis=1))
        lanes_out.append(lane._replace(
            clock=jnp.where(ctx.alive[:, None], new_clock, lane.clock),
            hist=plane_ops.where(ctx.alive[:, None], hist, lane.hist),
            hist_ptr=jnp.where(ctx.alive, hist_ptr, lane.hist_ptr),
            overflow=lane.overflow + comm.allsum(dropped)))
        # Remove from the event lane (overflow tail included: it was a
        # causal send, dropped and counted — it must not leak unicast).
        emitted = emitted.at[..., T.W_KIND].set(
            jnp.where(is_c_all, 0, emitted[..., T.W_KIND]))

    # 6. Point-to-point causal lanes, send side (emit, causality_backend
    #    :172-201): consume stream acks, stamp per-edge seq + epoch onto
    #    this round's p2p sends (go-back-N: a send only goes out if the
    #    unacked store has a slot for it), generate our own cumulative
    #    acks as a receiver, and put everything on the event lane.
    W = cfg.wire_words
    p2p_out = []
    for pi, lane in enumerate(st.p2p):
        lid = len(cfg.causal_labels) + pi
        DC, EC = cfg.p2p_dst_cap, cfg.p2p_emit_cap
        H = cfg.p2p_hist_cap

        # The whole send side runs under ONE lax.cond: a lane with no
        # unacked records, no arriving acks, no fresh sends and no
        # pending receiver work is completely idle — common for most of
        # a run (config 5's senders fire at two scheduled rounds), and
        # the idle machinery measured as a large share of the stacked
        # round (VERDICT r4 weak #4).  The predicate is a cross-shard
        # allsum (the body contains collectives).
        is_ack_in = (kind_in == T.MsgKind.P2P_ACK) \
            & ((inb[..., T.W_LANE] & 0xFF) == lid)
        is_p_pre = (emitted[..., T.W_KIND] != 0) \
            & (emitted[..., T.W_FLAGS] & T.F_CAUSAL != 0) \
            & (emitted[..., T.W_FLAGS] & T.F_P2P_STAMPED == 0) \
            & (emitted[..., T.W_LANE] == lid) & ctx.alive[:, None] \
            & (emitted[..., T.W_DST] >= 0)
        go_local = (jnp.any(lane.hist[..., T.W_KIND] != 0)
                    | jnp.any(is_ack_in) | jnp.any(is_p_pre)
                    | jnp.any(lane.reset_req >= 0) | jnp.any(lane.reack)
                    | jnp.any((lane.src_seq > lane.src_acked)
                              & (lane.src_ids >= 0)))
        lane_go = comm.allsum(go_local.astype(jnp.int32)) > 0

        def p2p_send_body(_, lane=lane, lid=lid, pi=pi,
                          is_ack_in=is_ack_in, emitted=emitted):
            # 6a. Consume arriving P2P_ACKs: free unacked records
            # covered by the cumulative (dst, epoch, seq) ack.  A
            # NEGATIVE ack clock is a stream-RESET request (the
            # receiver lost its watermark): the stream reopens under a
            # fresh epoch — its unacked records are re-stamped seq 1..
            # in order and replayed, so the undelivered prefix survives
            # (records the receiver delivered but whose ack was lost
            # re-deliver: the reset boundary is an at-least-once
            # window, see the class docstring).
            hist = lane.hist
            is_cum = is_ack_in & (inb[..., T.W_CLOCK] >= 0)
            is_rst = is_ack_in & (inb[..., T.W_CLOCK] < 0)
            h_dst = hist[..., T.W_DST]
            h_seq = hist[..., T.W_CLOCK]
            h_ep = (hist[..., T.W_LANE] >> 8) & _EPOCH_MASK
            covered = (
                is_cum[:, None, :]
                & (h_dst[:, :, None] == inb[..., T.W_SRC][:, None, :])
                & (h_ep[:, :, None] == ((inb[..., T.W_LANE] >> 8)
                                        & _EPOCH_MASK)[:, None, :])
                & (h_seq[:, :, None] <= inb[..., T.W_CLOCK][:, None, :])
            ).any(axis=2) & (hist[..., T.W_KIND] != 0)
            hist = hist.at[..., T.W_KIND].set(
                jnp.where(covered, 0, hist[..., T.W_KIND]))

            # Stream reopen: re-stamp every unacked record to a requesting
            # destination and reset the dst table entry.  A request names
            # the orphan seq k it observed (clock = -k); it acts ONLY when
            # nothing below k is still unacked here — if it is, this was
            # plain in-flight reordering and the ordinary go-back-N replay
            # recovers it (reopening then would re-deliver the prefix).
            h_dst = hist[..., T.W_DST]
            h_seq = hist[..., T.W_CLOCK]
            h_valid = hist[..., T.W_KIND] != 0
            rst_k = -inb[..., T.W_CLOCK]                           # [n, cap]
            below_unacked = (
                h_valid[:, :, None]
                & (h_dst[:, :, None] == inb[..., T.W_SRC][:, None, :])
                & (h_seq[:, :, None] < rst_k[:, None, :])
            ).any(axis=1)                                          # [n, cap]
            is_rst = is_rst & ~below_unacked
            rec_rst = h_valid & (
                is_rst[:, None, :]
                & (h_dst[:, :, None] == inb[..., T.W_SRC][:, None, :])
            ).any(axis=2)                                          # [n, H]
            reopen_ep = (rng_ops.rank32(ctx.seed, ctx.rnd,
                                        _P2P_REOPEN_TAG + pi,
                                        gids[:, None], jnp.maximum(h_dst, 0))
                         % jnp.uint32(_EPOCH_MASK) + 1).astype(jnp.int32)
            h_idx = jnp.arange(H)
            same_d = (h_dst[:, :, None] == h_dst[:, None, :]) \
                & rec_rst[:, :, None] & rec_rst[:, None, :]
            before = same_d & (
                (h_seq[:, None, :] < h_seq[:, :, None])
                | ((h_seq[:, None, :] == h_seq[:, :, None])
                   & (h_idx[None, None, :] < h_idx[None, :, None])))
            new_seq_r = jnp.sum(before, axis=2) + 1
            hist = hist.at[..., T.W_CLOCK].set(
                jnp.where(rec_rst, new_seq_r, hist[..., T.W_CLOCK]))
            hist = hist.at[..., T.W_LANE].set(
                jnp.where(rec_rst, lid | (reopen_ep << 8),
                          hist[..., T.W_LANE]))
            # dst-table reopen: clear every requested entry, then re-point
            # entries that still have records at (count, fresh epoch).
            tbl_rst = (is_rst[:, None, :]
                       & (lane.dst_ids[:, :, None]
                          == inb[..., T.W_SRC][:, None, :])).any(axis=2) \
                & (lane.dst_ids >= 0)                              # [n, DC]
            dst_ids0 = jnp.where(tbl_rst, -1, lane.dst_ids)
            dst_seq0 = jnp.where(tbl_rst, 0, lane.dst_seq)
            dst_ep0 = jnp.where(tbl_rst, 0, lane.dst_ep)
            hb_r = views.bucket_slot(jnp.maximum(h_dst, 0), DC)
            is_last_r = rec_rst & ~jnp.any(
                same_d & (new_seq_r[:, None, :] > new_seq_r[:, :, None]),
                axis=2)
            hit_r = is_last_r[:, None, :] & \
                (hb_r[:, None, :] == jnp.arange(DC)[None, :, None])
            anyhit_r = jnp.any(hit_r, axis=2)
            wslot_r = jnp.argmax(hit_r, axis=2)
            dst_ids0 = jnp.where(anyhit_r,
                                 jnp.take_along_axis(h_dst, wslot_r, axis=1),
                                 dst_ids0)
            dst_seq0 = jnp.where(anyhit_r,
                                 jnp.take_along_axis(new_seq_r, wslot_r,
                                                     axis=1), dst_seq0)
            dst_ep0 = jnp.where(anyhit_r,
                                jnp.take_along_axis(reopen_ep, wslot_r,
                                                    axis=1), dst_ep0)

            # A dead destination ends its streams: clear the table entries
            # so a recovered destination gets a FRESH stream (seq 1, new
            # epoch) instead of a watermark gap it can never fill.
            # BOTH per-destination liveness reads (the dst table's and
            # the unacked store's) ride ONE packed gather over the
            # concatenated id lists — the pack_wire_info discipline.
            alive_both = ctx.faults.alive[jnp.maximum(
                jnp.concatenate([dst_ids0, h_dst], axis=1), 0)]
            tbl_dead = (dst_ids0 >= 0) & ~alive_both[:, :DC]
            dst_ids0 = jnp.where(tbl_dead, -1, dst_ids0)
            dst_seq0 = jnp.where(tbl_dead, 0, dst_seq0)
            dst_ep0 = jnp.where(tbl_dead, 0, dst_ep0)

            # Abort unacked records whose stream is gone: the dst table no
            # longer tracks (dst, epoch) — bucket collision, reset, or the
            # destination died.
            h_ep2 = (hist[..., T.W_LANE] >> 8) & _EPOCH_MASK
            hb = views.bucket_slot(jnp.maximum(h_dst, 0), DC)
            hb_id = jnp.take_along_axis(dst_ids0, hb, axis=1)
            hb_ep = jnp.take_along_axis(dst_ep0, hb, axis=1)
            stream_live = (hb_id == h_dst) & (hb_ep == h_ep2) \
                & alive_both[:, DC:]
            aborted = (hist[..., T.W_KIND] != 0) & ~stream_live
            n_aborted = comm.allsum(jnp.sum(aborted, dtype=jnp.int32))
            hist = hist.at[..., T.W_KIND].set(
                jnp.where(aborted, 0, hist[..., T.W_KIND]))

            # Emit our own pending stream-reset requests (as a receiver).
            rr_ids = lane.reset_req
            rst_msgs = msg_ops.zero_wire(cfg, (n, rr_ids.shape[1]))
            rst_on = rr_ids >= 0
            rst_msgs = rst_msgs.at[..., T.W_KIND].set(
                jnp.where(rst_on, T.MsgKind.P2P_ACK, 0))
            rst_msgs = rst_msgs.at[..., T.W_SRC].set(
                jnp.where(rst_on, gids[:, None], 0))
            rst_msgs = rst_msgs.at[..., T.W_DST].set(
                jnp.where(rst_on, rr_ids, 0))
            rst_msgs = rst_msgs.at[..., T.W_CLOCK].set(
                jnp.where(rst_on, -jnp.maximum(lane.reset_seq, 1), 0))
            rst_msgs = rst_msgs.at[..., T.W_LANE].set(
                jnp.where(rst_on, lid, 0))
            rst_msgs = provenance_mod.stamp_fresh(cfg, rst_msgs)
            rst_msgs = latency_mod.stamp_fresh(cfg, rst_msgs, ctx.rnd)

            # 6b. Compact + admit this round's fresh sends against the free
            # store slots (drop visibly when full — never wedge a stream).
            is_p = (emitted[..., T.W_KIND] != 0) \
                & (emitted[..., T.W_FLAGS] & T.F_CAUSAL != 0) \
                & (emitted[..., T.W_FLAGS] & T.F_P2P_STAMPED == 0) \
                & (emitted[..., T.W_LANE] == lid) & ctx.alive[:, None] \
                & (emitted[..., T.W_DST] >= 0)
            packed, cap_dropped = _compact(emitted, is_p, EC)
            emitted = emitted.at[..., T.W_KIND].set(
                jnp.where(is_p, 0, emitted[..., T.W_KIND]))
            free = hist[..., T.W_KIND] == 0
            n_free = free.sum(axis=1, dtype=jnp.int32)
            valid0 = packed[..., T.W_KIND] != 0
            vrank = jnp.cumsum(valid0, axis=1) - 1
            kept = valid0 & (vrank < n_free[:, None])
            n_backpressured = comm.allsum(jnp.sum(valid0 & ~kept,
                                                  dtype=jnp.int32))
            packed = packed.at[..., T.W_KIND].set(
                jnp.where(kept, packed[..., T.W_KIND], 0))
            valid = kept

            # 6c. Stamp per-edge seq + stream epoch on the kept sends.
            d = packed[..., T.W_DST]
            b = views.bucket_slot(jnp.maximum(d, 0), DC)           # [n, EC]
            t_id = jnp.take_along_axis(dst_ids0, b, axis=1)
            tracked = (t_id == d) & valid
            cur_seq = jnp.where(tracked,
                                jnp.take_along_axis(dst_seq0, b, axis=1), 0)
            cur_ep = jnp.where(tracked,
                               jnp.take_along_axis(dst_ep0, b, axis=1), 0)
            fresh_ep = (rng_ops.rank32(ctx.seed, ctx.rnd, _P2P_EPOCH_TAG + pi,
                                       gids[:, None], jnp.maximum(d, 0))
                        % jnp.uint32(_EPOCH_MASK) + 1).astype(jnp.int32)
            ep = jnp.where(tracked, cur_ep, fresh_ep)
            # rank among same-destination sends this round (EC is tiny)
            ec_idx = jnp.arange(EC)
            samem = (d[:, :, None] == d[:, None, :]) \
                & valid[:, :, None] & valid[:, None, :]
            rank = jnp.sum(samem & (ec_idx[None, None, :] < ec_idx[None, :, None]),
                           axis=2)
            seq = cur_seq + rank + 1
            packed = packed.at[..., T.W_CLOCK].set(
                jnp.where(valid, seq, packed[..., T.W_CLOCK]))
            packed = packed.at[..., T.W_LANE].set(
                jnp.where(valid, lid | (ep << 8), packed[..., T.W_LANE]))
            packed = packed.at[..., T.W_FLAGS].set(
                jnp.where(valid, packed[..., T.W_FLAGS] | T.F_P2P_STAMPED,
                          packed[..., T.W_FLAGS]))

            # Table update: the LAST kept send per destination this round.
            is_last = valid & ~jnp.any(
                samem & (ec_idx[None, None, :] > ec_idx[None, :, None]), axis=2)
            hit = is_last[:, None, :] & \
                (b[:, None, :] == jnp.arange(DC)[None, :, None])   # [n, DC, EC]
            anyhit = jnp.any(hit, axis=2)
            wslot = jnp.argmax(hit, axis=2)                        # [n, DC]
            new_id = jnp.take_along_axis(d, wslot, axis=1)
            new_seq = jnp.take_along_axis(seq, wslot, axis=1)
            new_ep = jnp.take_along_axis(ep, wslot, axis=1)
            resets = comm.allsum(jnp.sum(
                anyhit & (dst_ids0 >= 0) & (dst_ids0 != new_id),
                dtype=jnp.int32))
            dst_ids = jnp.where(anyhit, new_id, dst_ids0)
            dst_seq = jnp.where(anyhit, new_seq, dst_seq0)
            dst_ep = jnp.where(anyhit, new_ep, dst_ep0)

            # 6d. Store kept sends into free slots; replay the whole unacked
            # store on the retransmit cadence (go-back-N re-send).
            rows_n2 = jnp.arange(n)[:, None]
            tgt = jnp.take_along_axis(
                _free_slot_of_rank(free), jnp.clip(vrank, 0, H - 1), axis=1)
            store_slot = jnp.where(kept, tgt, H)
            hist = hist.at[
                jnp.broadcast_to(rows_n2, store_slot.shape), store_slot
            ].set(packed, mode="drop")
            refire = ((ctx.rnd + gids) % cfg.retransmit_every == 0) & ctx.alive
            # Fresh records already went out this round via `packed`;
            # replaying them same-round is harmless (receivers dedup) but
            # wasteful, so exclude the slots just written.
            just_written = jnp.zeros((n, H), jnp.bool_).at[
                jnp.broadcast_to(rows_n2, store_slot.shape), store_slot
            ].set(True, mode="drop")
            live_slot = refire[:, None] & (hist[..., T.W_KIND] != 0) \
                & ~just_written
            replay = hist.at[..., T.W_FLAGS].set(
                hist[..., T.W_FLAGS] | T.F_RETRANSMISSION)
            replay = plane_ops.where(live_slot, replay, 0)

            # 6e. Receiver-side cumulative acks: on the retransmit cadence
            # (or sooner when a duplicate signalled a lost ack), ack every
            # tracked stream with unacked progress.
            ack_due = (lane.src_seq > lane.src_acked) & (lane.src_ids >= 0)
            ack_now = (ack_due & refire[:, None]) | \
                (lane.reack & (lane.src_ids >= 0))
            ack_msgs = msg_ops.zero_wire(cfg, (n, lane.src_ids.shape[1]))
            ack_msgs = ack_msgs.at[..., T.W_KIND].set(
                jnp.where(ack_now, T.MsgKind.P2P_ACK, 0))
            ack_msgs = ack_msgs.at[..., T.W_SRC].set(
                jnp.where(ack_now, gids[:, None], 0))
            ack_msgs = ack_msgs.at[..., T.W_DST].set(
                jnp.where(ack_now, lane.src_ids, 0))
            ack_msgs = ack_msgs.at[..., T.W_CLOCK].set(
                jnp.where(ack_now, lane.src_seq, 0))
            ack_msgs = ack_msgs.at[..., T.W_LANE].set(
                jnp.where(ack_now, lid | (lane.src_ep << 8), 0))
            ack_msgs = provenance_mod.stamp_fresh(cfg, ack_msgs)
            ack_msgs = latency_mod.stamp_fresh(cfg, ack_msgs, ctx.rnd)
            src_acked = jnp.where(ack_now, lane.src_seq, lane.src_acked)

            alive1 = ctx.alive[:, None]
            new_lane = lane._replace(
                dst_ids=jnp.where(alive1, dst_ids, lane.dst_ids),
                dst_seq=jnp.where(alive1, dst_seq, lane.dst_seq),
                dst_ep=jnp.where(alive1, dst_ep, lane.dst_ep),
                src_acked=jnp.where(alive1, src_acked, lane.src_acked),
                reack=jnp.where(alive1, lane.reack & ~ack_now,
                                lane.reack),
                reset_req=jnp.where(alive1,
                                    jnp.full_like(lane.reset_req, -1),
                                    lane.reset_req),
                hist=plane_ops.where(alive1, hist, lane.hist),
                overflow=lane.overflow + comm.allsum(cap_dropped)
                + n_backpressured,
                resets=lane.resets + resets,
                aborted=lane.aborted + n_aborted)
            return new_lane, packed, replay, ack_msgs, rst_msgs, emitted

        def p2p_send_skip(_, lane=lane):
            return (lane,
                    msg_ops.zero_wire(cfg, (n, EC)),
                    msg_ops.zero_wire(cfg, (n, H)),
                    msg_ops.zero_wire(cfg, (n, lane.src_ids.shape[1])),
                    msg_ops.zero_wire(cfg, (n, lane.reset_req.shape[1])),
                    emitted)

        lane_f, packed, replay, ack_msgs, rst_msgs, emitted = \
            jax.lax.cond(lane_go, p2p_send_body, p2p_send_skip, 0)
        p2p_out.append(lane_f)
        extra.append(packed)
        extra.append(replay)
        extra.append(ack_msgs)
        extra.append(rst_msgs)

    # Any message still flagged F_CAUSAL (and not a stamped p2p record)
    # was emitted by a non-actor node or names an unconfigured lane: it
    # must NOT leak onto the unicast path unordered.  Drop + account.
    invalid = jnp.int32(0)
    if st.lanes or st.p2p:
        leak = (emitted[..., T.W_KIND] != 0) \
            & (emitted[..., T.W_FLAGS] & T.F_CAUSAL != 0) \
            & (emitted[..., T.W_FLAGS] & T.F_P2P_STAMPED == 0)
        invalid = comm.allsum(jnp.sum(leak, dtype=jnp.int32))
        emitted = emitted.at[..., T.W_KIND].set(
            jnp.where(leak, 0, emitted[..., T.W_KIND]))

    if extra:
        emitted = plane_ops.concat([emitted] + extra, axis=1)
    return (DeliveryState(ack=ack, lanes=tuple(lanes_out),
                          p2p=tuple(p2p_out),
                          invalid_causal=st.invalid_causal + invalid),
            emitted, tuple(wide_out))


# ---------------------------------------------------------------------------
# Inbound: dense vectorized causal delivery
# ---------------------------------------------------------------------------

def _fetch(buf, shared, idx: Array):
    """Per-node record fetch over the combined candidate index space:
    ``idx < B`` reads the node's buffer row, else the shared table.
    buf [n, B, w], shared [G, w], idx [n, D] -> [n, D, w].

    Plane-major records ride TWO dtype-grouped fill-gathers (one per
    source) whose out-of-branch entries fill 0, merged by an exact
    disjoint ADD — previously every plane paid its own pair of gathers
    plus a pair of selects (2·(W+A) gather eqns per fetch on the causal
    lanes; the round-cost meter's coalescing rule)."""
    B = buf.shape[1]
    G = shared.shape[0]
    if plane_ops.is_planes(buf):
        n = buf.shape[0]
        rows = jnp.arange(n, dtype=jnp.int32)[:, None]
        in_b = idx < B
        in_s = (idx >= B) & (idx < B + G)
        pos_b = jnp.where(in_b, jnp.clip(idx, 0, B - 1) + rows * B,
                          n * B)
        pos_s = jnp.where(in_s, idx - B, G)
        flat_b = plane_ops.Planes(tuple(w.reshape(-1) for w in buf.ws))
        gb = plane_ops.take_flat(flat_b, pos_b, fill=True)
        gs = plane_ops.take_flat(shared, pos_s, fill=True)
        return plane_ops.Planes(tuple(
            b + s for b, s in zip(gb.ws, gs.ws)))
    ib = jnp.clip(idx, 0, B - 1)
    is_ = jnp.clip(idx - B, 0, G - 1)
    from_buf = jnp.take_along_axis(buf, ib[..., None], axis=1)
    from_shared = shared[is_]
    out = jnp.where((idx < B)[..., None], from_buf, from_shared)
    return jnp.where((idx < B + G)[..., None], out, 0)


def inbound(cfg: Config, comm, st: DeliveryState, inbox: exchange.Inbox,
            wides: tuple, ctx: RoundCtx
            ) -> tuple[DeliveryState, exchange.Inbox, Array]:
    """Causal receive path: combine this round's records from all actors
    into one shared table, run dense deliverability sweeps for every
    node at once, merge deliveries (in causal order) into the
    model-visible inbox, buffer out-of-order futures.  Also returns the
    global count of causal deliveries this round (for Stats)."""
    W = cfg.wire_words
    A = cfg.n_actors
    B = cfg.causal_buf_cap
    n = comm.n_local
    gids = comm.local_ids()
    rows_n = jnp.arange(n)[:, None]

    n_causal = jnp.int32(0)
    lanes_out = []
    for li, (lane, payload) in enumerate(zip(st.lanes, wides)):
        # Shared candidate table: every actor's records this round.
        shared = comm.actor_gather(payload, A)      # [A, Ec+H, W+A]
        shared = shared.reshape(-1, W + A)
        G = shared.shape[0]
        s_msg = shared[:, :W]
        s_clk = plane_ops.stack_words(shared, W).astype(vclock.DTYPE)
        s_src = jnp.minimum(jnp.maximum(s_msg[:, T.W_SRC], 0), A - 1)
        s_cnt = s_clk[jnp.arange(G), s_src]
        s_dep = s_clk.at[jnp.arange(G), s_src].set(0)   # deps w/o sender
        s_valid = s_msg[:, T.W_KIND] != 0

        # Per-receiver transmission faults: each record's arrival at each
        # node rides the (src -> node) edge this round (replays re-ride
        # it next tick — loss is per-transmission, as on a real link).
        cut = faults_mod.edge_cut(
            ctx.faults,
            jnp.broadcast_to(s_msg[None, :, T.W_SRC], (n, G)),
            jnp.where(s_valid[None, :], gids[:, None], -1),
            ctx.seed, ctx.rnd, _CAUSAL_SALT + li)
        arr_ok = s_valid[None, :] & ~cut & ctx.alive[:, None]

        # Buffered candidates (already arrived in earlier rounds).
        b_msg = lane.buf[..., :W]
        b_clk = plane_ops.stack_words(lane.buf, W).astype(vclock.DTYPE)
        b_src = jnp.minimum(jnp.maximum(b_msg[..., T.W_SRC], 0), A - 1)
        b_cnt = jnp.take_along_axis(b_clk, b_src[..., None], axis=2)[..., 0]
        b_dep = jnp.where(
            (jnp.arange(A)[None, None, :] == b_src[..., None]), 0, b_clk)
        b_valid = b_msg[..., T.W_KIND] != 0

        clock0 = lane.clock
        INF = jnp.int32(B + G + 1)
        D = min(B + G, cfg.causal_deliver_cap)
        # The per-node quota is bounded by the inbox space actually left
        # after the event lane (and prior lanes) — a record whose clock
        # advance survived but whose payload got cut at the merge would
        # be a silent zero-times delivery.
        free = jnp.maximum(cfg.inbox_cap - inbox.count, 0)
        quota0 = jnp.minimum(jnp.int32(D), free)

        def sweep(carry):
            clock, b_avail, s_avail, quota = carry
            loc_b = jnp.take_along_axis(clock, b_src, axis=1)
            loc_s = clock[:, s_src]                      # [n, G]
            ok_b = b_avail & (b_cnt == loc_b + 1) & \
                jnp.all(b_dep <= clock[:, None, :], axis=2)
            ok_s = s_avail & (s_cnt[None, :] == loc_s + 1) & \
                jnp.all(s_dep[None] <= clock[:, None, :], axis=2)
            # Dedup per (node, sender): lowest combined index wins
            # (buffered records are older -> priority).
            ib = jnp.where(ok_b, jnp.arange(B)[None, :], INF)
            is_ = jnp.where(ok_s, B + jnp.arange(G)[None, :], INF)
            win = jnp.full((n, A), INF, jnp.int32)
            win = win.at[jnp.broadcast_to(rows_n, b_src.shape), b_src
                         ].min(ib)
            win = win.at[jnp.broadcast_to(rows_n, (n, G)),
                         jnp.broadcast_to(s_src[None, :], (n, G))
                         ].min(is_)
            # Delivery quota: the round delivers at most D records per
            # node (the inbox-merge capacity).  Winners beyond the
            # remaining quota stay undelivered — their clocks do NOT
            # advance, so they re-buffer as futures and deliver next
            # round.  Rank winners by index for a deterministic cut.
            rank = jnp.sum((win[:, None, :] < win[:, :, None]), axis=2)
            deliver = (win < INF) & (rank < quota[:, None])
            del_b = ok_b & (ib == jnp.take_along_axis(win, b_src, axis=1)) \
                & jnp.take_along_axis(deliver, b_src, axis=1)
            del_s = ok_s & (is_ == win[:, s_src]) & deliver[:, s_src]
            mb = jnp.max(jnp.where(del_b[..., None], b_clk, 0), axis=1)
            ms = jnp.max(jnp.where(del_s[..., None], s_clk[None], 0),
                         axis=1)
            clock2 = jnp.maximum(clock, jnp.maximum(mb, ms))
            quota2 = quota - jnp.sum(deliver, axis=1, dtype=jnp.int32)
            return (clock2, b_avail & ~del_b, s_avail & ~del_s, quota2), \
                (del_b, del_s)

        b_avail, s_avail = b_valid & ctx.alive[:, None], arr_ok
        clock = clock0
        quota = quota0
        dels = []
        for _ in range(CAUSAL_SWEEPS):
            (clock, b_avail, s_avail, quota), d = sweep(
                (clock, b_avail, s_avail, quota))
            dels.append(d)
        clock_f = jnp.where(ctx.alive[:, None], clock, clock0)

        # Delivery order = (sweep, combined index).
        def order_key(del_list, idx_base, count):
            key = jnp.full((n, count), jnp.int32(2**30))
            for s_i, d in enumerate(del_list):
                k = s_i * (B + G) + idx_base
                key = jnp.minimum(key, jnp.where(d, k, 2**30))
            return key

        key_b = order_key([d[0] for d in dels],
                          jnp.arange(B)[None, :], B)
        key_s = order_key([d[1] for d in dels],
                          B + jnp.arange(G)[None, :], G)
        keys = jnp.concatenate([key_b, key_s], axis=1)     # [n, B+G]
        # top_k of -keys yields the SMALLEST keys first = delivery order;
        # the returned positions ARE combined candidate indices.
        topv, topi = jax.lax.top_k(-keys, D)
        deliv_idx = jnp.where(-topv < 2**30, topi, B + G + 1)
        recs = _fetch(lane.buf, shared, deliv_idx)
        dmsgs = recs[..., :W]
        n_deliv = jnp.sum(keys < 2**30, axis=1, dtype=jnp.int32)
        n_causal = n_causal + comm.allsum(jnp.sum(n_deliv))
        inbox = exchange.merge_inboxes(
            inbox,
            exchange.Inbox(
                data=dmsgs,
                count=jnp.minimum(n_deliv, D),
                drops=jnp.zeros_like(inbox.drops)))

        # Buffer the undelivered futures (stale ones vanish).  Dedup by
        # (sender, counter-offset): replay cycles re-deliver copies of a
        # blocked message every tick — only one copy may occupy a slot
        # (buffered copies, having lower combined index, win).  Offsets
        # beyond B can't deliver before nearer ones fill the buffer, so
        # they're shed and recovered by a later replay.
        loc_bf = jnp.take_along_axis(clock_f, b_src, axis=1)
        off_b = b_cnt.astype(jnp.int32) - loc_bf.astype(jnp.int32)
        off_s = s_cnt[None, :].astype(jnp.int32) - \
            clock_f[:, s_src].astype(jnp.int32)
        fut_b = b_valid & b_avail & (off_b >= 1) & (off_b <= B)
        fut_s = arr_ok & s_avail & (off_s >= 1) & (off_s <= B)
        idx_b = jnp.broadcast_to(jnp.arange(B)[None, :], (n, B))
        idx_s = jnp.broadcast_to(B + jnp.arange(G)[None, :], (n, G))
        tab = jnp.full((n, A, B), INF, jnp.int32)
        tab = tab.at[jnp.broadcast_to(rows_n, (n, B)), b_src,
                     jnp.clip(off_b - 1, 0, B - 1)
                     ].min(jnp.where(fut_b, idx_b, INF))
        tab = tab.at[jnp.broadcast_to(rows_n, (n, G)),
                     jnp.broadcast_to(s_src[None, :], (n, G)),
                     jnp.clip(off_s - 1, 0, B - 1)
                     ].min(jnp.where(fut_s, idx_s, INF))
        keep_b = fut_b & (idx_b == tab[
            jnp.broadcast_to(rows_n, (n, B)), b_src,
            jnp.clip(off_b - 1, 0, B - 1)])
        keep_s = fut_s & (idx_s == tab[
            jnp.broadcast_to(rows_n, (n, G)),
            jnp.broadcast_to(s_src[None, :], (n, G)),
            jnp.clip(off_s - 1, 0, B - 1)])
        fkeys = jnp.concatenate(
            [jnp.where(keep_b, idx_b, INF),
             jnp.where(keep_s, idx_s, INF)], axis=1)
        ftop, fidx = jax.lax.top_k(-fkeys, B)
        keep_idx = jnp.where(-ftop < INF, fidx, B + G + 1)
        new_buf = _fetch(lane.buf, shared, keep_idx)
        n_fut = jnp.sum(fkeys < INF, axis=1, dtype=jnp.int32)
        buf_overflow = comm.allsum(jnp.sum(
            jnp.maximum(n_fut - B, 0), dtype=jnp.int32))

        new_buf = plane_ops.where(ctx.alive[:, None], new_buf, lane.buf)
        lanes_out.append(lane._replace(
            clock=clock_f,
            buf=new_buf,
            overflow=lane.overflow + buf_overflow,
        ))

    # ---- point-to-point lanes (receive side of the per-destination
    # scheme, causality_backend :204-220 + :309-344): candidates = this
    # round's routed arrivals + the out-of-order buffer; a record
    # delivers when its stream is in order (seq == last+1), a new or
    # re-epoched stream delivers its first (lowest-seq) arrival
    # immediately, covered seqs drop as replay duplicates, futures
    # re-buffer.
    p2p_out = []
    for pi, lane in enumerate(st.p2p):
        lid = len(cfg.causal_labels) + pi
        SC, B2 = cfg.p2p_src_cap, cfg.p2p_buf_cap
        msgs = inbox.data
        cap = msgs.shape[1]
        flagsm = msgs[..., T.W_FLAGS]
        is_p = (msgs[..., T.W_KIND] != 0) \
            & (flagsm & T.F_CAUSAL != 0) \
            & (flagsm & T.F_P2P_STAMPED != 0) \
            & ((msgs[..., T.W_LANE] & 0xFF) == lid)
        # Idle receive side skips the 3-sweep machinery outright: no
        # stamped arrivals and nothing buffered means the lane state
        # and the inbox pass through unchanged (cross-shard pred — the
        # body contains collectives).
        rgo_local = jnp.any(is_p) | jnp.any(lane.buf[..., T.W_KIND] != 0)
        lane_rgo = comm.allsum(rgo_local.astype(jnp.int32)) > 0

        def p2p_recv_body(_, lane=lane, lid=lid, pi=pi, is_p=is_p,
                          msgs=msgs, inbox=inbox, n_causal=n_causal):
            cmsg = plane_ops.concat(
                [plane_ops.where(is_p, msgs, 0), lane.buf], axis=1)
            C = cmsg.shape[1]
            cvalid = cmsg[..., T.W_KIND] != 0
            csrc = cmsg[..., T.W_SRC]
            cseq = cmsg[..., T.W_CLOCK]
            cep = (cmsg[..., T.W_LANE] >> 8) & _EPOCH_MASK
            if C > 2048:
                # Key arithmetic below packs (sweep, clamped seq, slot) into
                # int32; C beyond this would overflow the packing silently.
                raise ValueError(
                    f"p2p causal lanes need inbox_cap + p2p_buf_cap <= 2048 "
                    f"(got {C})")
            sb = views.bucket_slot(jnp.maximum(csrc, 0), SC)       # [n, C]
            c_idx = jnp.arange(C)[None, :]
            sc_idx = jnp.arange(SC)[None, :, None]
            hitm = (sb[:, None, :] == sc_idx)                      # [n, SC, C]
            INF2 = jnp.int32(2**31 - 1)
            # Sort keys clamp the (unbounded) seq so they stay below the
            # sentinel (max okey = 2*C*(2^18+1) + ckey < 2^31 for C <=
            # 2048); within one sender only ONE record is in-order-eligible
            # at a time, so clamped ties cannot reorder a stream.
            ckey = jnp.minimum(cseq, 1 << 18) * C + c_idx

            # Inbox-space quota BEFORE any table advance: a record counts as
            # delivered only if it actually reaches the app this round —
            # winners beyond the quota stay buffered with their stream
            # position intact (the broadcast lane's quota contract).
            base = exchange.Inbox(
                data=plane_ops.where(is_p, 0, msgs),
                count=jnp.sum((msgs[..., T.W_KIND] != 0) & ~is_p, axis=1,
                              dtype=jnp.int32),
                drops=inbox.drops)
            D2 = min(C, cfg.causal_deliver_cap)
            quota0 = jnp.minimum(jnp.int32(D2),
                                 jnp.maximum(cfg.inbox_cap - base.count, 0))

            def p2p_sweep(carry):
                s_ids, s_seq, s_ep, avail, quota, reack = carry
                t_id = jnp.take_along_axis(s_ids, sb, axis=1)
                t_seq = jnp.take_along_axis(s_seq, sb, axis=1)
                t_ep = jnp.take_along_axis(s_ep, sb, axis=1)
                tracked = (t_id == csrc) & cvalid
                same_ep = tracked & (t_ep == cep)
                dup = same_ep & (cseq <= t_seq) & avail
                inorder = same_ep & (cseq == t_seq + 1)
                # A stream OPENS only at seq 1 (every fresh epoch starts
                # there); an untracked mid-sequence arrival means WE lost
                # the watermark — it buffers and triggers a stream-reset
                # request below, never an out-of-order delivery that would
                # strand the prefix.
                newstream = cvalid & (~tracked | (tracked & ~same_ep)) \
                    & (cseq == 1)
                elig = avail & (inorder | newstream) & ~dup
                # One winner per sender bucket per sweep: lowest (seq, idx).
                key = jnp.where(elig, ckey, INF2)
                keymat = jnp.where(hitm, key[:, None, :], INF2)
                best = jnp.min(keymat, axis=2)                     # [n, SC]
                win = elig & (key == jnp.take_along_axis(best, sb, axis=1))
                # Quota cut: rank winners by key, keep the first `quota`.
                wrank = jnp.sum(
                    (jnp.where(win, key, INF2)[:, None, :]
                     < jnp.where(win, key, INF2)[:, :, None]), axis=2)
                deliver = win & (wrank < quota[:, None])
                # Update tables only for buckets whose winner DELIVERED.
                dkeymat = jnp.where(
                    hitm & deliver[:, None, :], key[:, None, :], INF2)
                dbest = jnp.min(dkeymat, axis=2)
                got = dbest < INF2
                wslot = jnp.argmin(dkeymat, axis=2)                # [n, SC]
                s_ids2 = jnp.where(got, jnp.take_along_axis(csrc, wslot, 1),
                                   s_ids)
                s_seq2 = jnp.where(got, jnp.take_along_axis(cseq, wslot, 1),
                                   s_seq)
                s_ep2 = jnp.where(got, jnp.take_along_axis(cep, wslot, 1),
                                  s_ep)
                # A duplicate means our last ack may have been lost: re-ack.
                dup_hit = jnp.any(hitm & dup[:, None, :], axis=2)
                reack2 = reack | (dup_hit & (s_ids >= 0))
                quota2 = quota - jnp.sum(deliver, axis=1, dtype=jnp.int32)
                return (s_ids2, s_seq2, s_ep2, avail & ~deliver & ~dup,
                        quota2, reack2), (deliver, dup)

            carry = (lane.src_ids, lane.src_seq, lane.src_ep,
                     cvalid & ctx.alive[:, None], quota0, lane.reack)
            dels = []
            for _ in range(CAUSAL_SWEEPS):
                carry, d = p2p_sweep(carry)
                dels.append(d[0])
            s_ids_f, s_seq_f, s_ep_f, avail_f, _, reack_f = carry
            resets = comm.allsum(jnp.sum(
                (lane.src_ids >= 0) & (s_ids_f != lane.src_ids),
                dtype=jnp.int32))

            # Delivery order = (sweep, key); strip the epoch bits from
            # W_LANE so apps see the plain lane id.
            okey = jnp.full((n, C), INF2)
            for s_i, d in enumerate(dels):
                okey = jnp.minimum(
                    okey, jnp.where(d, s_i * (C * ((1 << 18) + 1)) + ckey,
                                    INF2))
            topv, topi = jax.lax.top_k(-okey, D2)
            drecs = plane_ops.take_rows(
                cmsg, jnp.where(-topv < INF2, topi, C), fill=True)
            drecs = drecs.at[..., T.W_LANE].set(
                jnp.where(drecs[..., T.W_KIND] != 0, lid,
                          drecs[..., T.W_LANE]))
            n_deliv = jnp.sum(okey < INF2, axis=1, dtype=jnp.int32)
            # Stats netting: routed p2p arrivals were already counted by the
            # event lane's delivered counter when they landed in the inbox;
            # this lane's NET contribution is app deliveries minus the
            # arrivals it pulled back out (buffered records count the round
            # they finally deliver).
            n_causal = n_causal + comm.allsum(
                jnp.sum(n_deliv) - jnp.sum(is_p, dtype=jnp.int32))

            # Rebuild the inbox: p2p slots out, deliveries (in order) in.
            inbox = exchange.merge_inboxes(base, exchange.Inbox(
                data=drecs, count=jnp.minimum(n_deliv, D2),
                drops=jnp.zeros_like(inbox.drops)))

            # Futures re-buffer by key order; overflow sheds (the sender's
            # unacked store recovers them on the next replay tick).
            fkey = jnp.where(avail_f & cvalid, ckey, INF2)
            ftop, fidx = jax.lax.top_k(-fkey, B2)
            new_buf = plane_ops.take_rows(
                cmsg, jnp.where(-ftop < INF2, fidx, C), fill=True)
            n_fut = jnp.sum(fkey < INF2, axis=1, dtype=jnp.int32)
            shed = comm.allsum(jnp.sum(jnp.maximum(n_fut - B2, 0),
                                       dtype=jnp.int32))

            # Collect stream-reset requests: candidates still pending whose
            # stream we cannot place (untracked / re-epoched, mid-sequence).
            ft_id = jnp.take_along_axis(s_ids_f, sb, axis=1)
            ft_ep = jnp.take_along_axis(s_ep_f, sb, axis=1)
            orphan = avail_f & cvalid & (cseq > 1) \
                & ((ft_id != csrc) | (ft_ep != cep))
            # first occurrence per sender (duplicate requests waste slots)
            same_src = (csrc[:, :, None] == csrc[:, None, :]) \
                & orphan[:, :, None] & orphan[:, None, :]
            earlier = same_src & (jnp.arange(C)[None, None, :]
                                  < jnp.arange(C)[None, :, None])
            orphan = orphan & ~jnp.any(earlier, axis=2)
            rst_pack, _ = _compact(
                jnp.stack([csrc + 1, cseq], axis=-1), orphan,
                _P2P_RESET_SLOTS)
            rst_ids = rst_pack[..., 0] - 1                         # -1 = none
            rst_seqs = rst_pack[..., 1]

            alive1 = ctx.alive[:, None]
            # A reassigned bucket's ack watermark belongs to the OLD stream.
            src_acked_f = jnp.where(s_ids_f != lane.src_ids, 0,
                                    lane.src_acked)
            new_lane = lane._replace(
                src_ids=jnp.where(alive1, s_ids_f, lane.src_ids),
                src_seq=jnp.where(alive1, s_seq_f, lane.src_seq),
                src_ep=jnp.where(alive1, s_ep_f, lane.src_ep),
                src_acked=jnp.where(alive1, src_acked_f, lane.src_acked),
                reack=jnp.where(alive1, reack_f, lane.reack),
                reset_req=jnp.where(alive1, rst_ids, lane.reset_req),
                reset_seq=jnp.where(alive1, rst_seqs, lane.reset_seq),
                buf=plane_ops.where(alive1, new_buf, lane.buf),
                overflow=lane.overflow + shed,
                resets=lane.resets + resets)
            return new_lane, inbox, n_causal

        def p2p_recv_skip(_, lane=lane):
            return lane, inbox, n_causal

        lane_f, inbox, n_causal = jax.lax.cond(
            lane_rgo, p2p_recv_body, p2p_recv_skip, 0)
        p2p_out.append(lane_f)

    return st._replace(lanes=tuple(lanes_out), p2p=tuple(p2p_out)), \
        inbox, n_causal


# ---------------------------------------------------------------------------
# Metrics-plane accounting
# ---------------------------------------------------------------------------

def overflow_total(st) -> Array:
    """int32: every cumulative drop counter of the delivery plane summed
    — ack-store overflow, causal-lane emit/buffer overflow, p2p
    overflow + aborted records, invalid-causal sheds.  Each summand is
    ``comm.allsum``-maintained, so the total is replicated; the metrics
    plane records its per-round delta as the ``dlv_overflow`` series.
    Accepts ``()`` (delivery disabled) and returns 0."""
    if st == ():
        return jnp.int32(0)
    total = st.invalid_causal
    if st.ack != ():
        total = total + st.ack.overflow
    for lane in st.lanes:
        total = total + lane.overflow
    for lane in st.p2p:
        total = total + lane.overflow + lane.aborted
    return total
