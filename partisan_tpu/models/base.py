"""Workload model interface.

Each reference protocol (protocols/*.erl) is a gen_server per node using
``partisan:forward_message`` + membership callbacks; here a model is a pure
per-round transition over node-axis arrays, given the manager's current
overlay ``nbrs`` (the members/neighbors callback analogue)."""

from __future__ import annotations

from typing import Any, Protocol

from jax import Array

from partisan_tpu.comm import LocalComm
from partisan_tpu.config import Config
from partisan_tpu.managers.base import RoundCtx


class Model(Protocol):
    def init(self, cfg: Config, comm: LocalComm) -> Any:
        ...

    def step(self, cfg: Config, comm: LocalComm, state: Any, ctx: RoundCtx,
             nbrs: Array) -> tuple[Any, Array]:
        """Returns (state', emitted int32[n_local, E, W])."""
        ...
