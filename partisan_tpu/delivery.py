"""Delivery semantics: acked delivery with retransmission, and causal
broadcast lanes.

TPU rebuild of two reference backends that wrap the send path:

- **Acked delivery** (partisan_acknowledgement_backend.erl:70-85, driven
  by the pluggable manager: store-on-send :1290-1307, retransmit timer
  :1421-1470, receiver ack + deliver :1835-1881): a message sent with
  ``F_ACK_REQUIRED`` is stored by the sender keyed by its per-sender
  monotonic clock; every ``retransmit_interval`` it is re-sent (flagged
  ``F_RETRANSMISSION``) until the matching ``ACK`` arrives.  Delivery is
  at-least-once — receivers may see duplicates, exactly as in the
  reference fast path.

- **Causal delivery** (partisan_causality_backend.erl: emit stores the
  stamped message for re-emission :172-201, receive buffers until
  dependencies are satisfied :204-220 + :309-344, delivery merges clocks
  :263-300).  The reference's scheme is point-to-point with
  per-destination dependency clocks and a *dominance* check that can be
  satisfied transitively without the dependency being delivered — an
  approximation it acknowledges.  The TPU lane targets the headline
  workload instead (causal **broadcast** at cluster scale, driver config
  #5) and implements exact vector-clock causal broadcast: each logical
  message increments its sender's entry once, every node delivers it at
  most once, in causal order, buffering out-of-order arrivals.  Senders
  must live in the bounded actor space (``gid < cfg.n_actors``); anyone
  receives.  Loss recovery is sender-side: every stamped record enters a
  history ring replayed on the retransmit cadence (the order-buffer-on-
  the-wire analogue, wire format :115), and receivers stale-drop
  already-covered counters, making replay idempotent — app-visible
  delivery is exactly-once, in causal order.

Tensor mapping: a causal record is ``[msg_words + n_actors]`` int32 (the
event words followed by the clock).  Per round, each lane's records from
ALL actors are combined into ONE shared candidate table (an ``lax.psum``
over the shard axis — actors are zero-padded rows off their home shard),
and deliverability for every (node, candidate) pair is evaluated as a
dense vectorized sweep — no per-node scans.  ``CAUSAL_SWEEPS`` sweeps
per round bound in-round chain delivery; longer chains resume next
round, like the reference's redelivery timer (:303-306).

Models opt in per message via flags: ``F_ACK_REQUIRED`` for acked sends;
``F_CAUSAL`` (+ ``W_LANE`` = label index) emits ONE record per logical
broadcast (the destination word is ignored — every node is a receiver;
the sender's own copy is suppressed by the stale-drop since its clock
already covers it).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import Array

from partisan_tpu import faults as faults_mod
from partisan_tpu import types as T
from partisan_tpu.config import Config
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.ops import exchange, vclock

CAUSAL_SWEEPS = 3     # in-round delivery passes (chain depth per round)
_CAUSAL_SALT = 21     # fault-filter call-site salt for causal lanes


class AckState(NamedTuple):
    outstanding: Array  # int32[n_local, ack_cap, W] — kind==NONE = free slot
    next_clock: Array   # int32[n_local] — next per-sender message clock
    overflow: Array     # int32 — acked sends dropped: store was full


class CausalLane(NamedTuple):
    clock: Array      # uint32[n_local, A] — delivered-state vclock
    buf: Array        # int32[n_local, B, W+A] — out-of-order arrivals
    hist: Array       # int32[n_local, H, W+A] — sent-record replay ring
    hist_ptr: Array   # int32[n_local] — ring write position
    overflow: Array   # int32 — records dropped: emit/buffer slots full


class DeliveryState(NamedTuple):
    ack: AckState | tuple
    lanes: tuple           # one CausalLane per cfg.causal_labels entry
    invalid_causal: Array  # int32 — F_CAUSAL sends dropped (non-actor
                           #   sender or unconfigured lane)


def enabled(cfg: Config) -> bool:
    return cfg.ack_cap > 0 or bool(cfg.causal_labels)


def init(cfg: Config, comm) -> DeliveryState:
    n = comm.n_local
    WA = cfg.msg_words + cfg.n_actors
    ack = AckState(
        outstanding=jnp.zeros((n, cfg.ack_cap, cfg.msg_words), jnp.int32),
        next_clock=jnp.ones((n,), jnp.int32),
        overflow=jnp.int32(0),
    ) if cfg.ack_cap > 0 else ()
    lanes = tuple(
        CausalLane(
            clock=vclock.fresh_matrix(n, cfg.n_actors),
            buf=jnp.zeros((n, cfg.causal_buf_cap, WA), jnp.int32),
            hist=jnp.zeros((n, cfg.causal_hist_cap, WA), jnp.int32),
            hist_ptr=jnp.zeros((n,), jnp.int32),
            overflow=jnp.int32(0),
        )
        for _ in cfg.causal_labels
    )
    return DeliveryState(ack=ack, lanes=lanes,
                         invalid_causal=jnp.int32(0))


def _compact(rows: Array, mask: Array, cap: int) -> tuple[Array, Array]:
    """Per-node: gather ``rows[i, e]`` where ``mask`` into ``cap`` slots,
    preserving slot order.  Returns (packed [n, cap, w], n_dropped)."""
    n, e, w = rows.shape
    rank = jnp.cumsum(mask, axis=1) - 1
    slot = jnp.where(mask, rank, e + cap)
    out = jnp.zeros((n, cap, w), rows.dtype)
    rows_idx = jnp.broadcast_to(jnp.arange(n)[:, None], (n, e))
    out = out.at[rows_idx, slot].set(rows, mode="drop")
    dropped = jnp.sum(jnp.maximum(
        jnp.sum(mask, axis=1) - cap, 0), dtype=jnp.int32)
    return out, dropped


# ---------------------------------------------------------------------------
# Outbound
# ---------------------------------------------------------------------------

def outbound(cfg: Config, comm, st: DeliveryState, emitted: Array,
             ctx: RoundCtx) -> tuple[DeliveryState, Array, tuple]:
    """Process the send path.  Returns (state', emitted', wide_per_lane):
    ack/retransmit records are appended to ``emitted``; causal messages
    are REMOVED from it and returned as per-lane wide-record tensors."""
    gids = comm.local_ids()
    n = emitted.shape[0]
    inb = ctx.inbox.data
    flags_in = inb[..., T.W_FLAGS]
    kind_in = inb[..., T.W_KIND]

    extra = []
    ack = st.ack
    if cfg.ack_cap > 0:
        # 1. Ack everything that arrived flagged (receiver side,
        #    pluggable :1835-1846).  Duplicates re-ack — the reference
        #    acks retransmissions too.
        need_ack = (kind_in != 0) & (flags_in & T.F_ACK_REQUIRED != 0) \
            & ctx.alive[:, None]
        ack_msgs = jnp.zeros_like(inb)
        ack_msgs = ack_msgs.at[..., T.W_KIND].set(
            jnp.where(need_ack, T.MsgKind.ACK, 0))
        ack_msgs = ack_msgs.at[..., T.W_SRC].set(
            jnp.where(need_ack, gids[:, None], 0))
        ack_msgs = ack_msgs.at[..., T.W_DST].set(
            jnp.where(need_ack, inb[..., T.W_SRC], 0))
        ack_msgs = ack_msgs.at[..., T.W_CLOCK].set(
            jnp.where(need_ack, inb[..., T.W_CLOCK], 0))
        extra.append(ack_msgs)

        # 2. Consume arriving ACKs: clear matching outstanding slots
        #    (match on clock + the acker being the stored destination).
        is_ack = kind_in == T.MsgKind.ACK
        out = ack.outstanding
        hit = (
            (out[..., T.W_CLOCK][:, :, None] == inb[..., T.W_CLOCK][:, None, :])
            & (out[..., T.W_DST][:, :, None] == inb[..., T.W_SRC][:, None, :])
            & is_ack[:, None, :]
            & (out[..., T.W_KIND][:, :, None] != 0)
        ).any(axis=2)
        out = out.at[..., T.W_KIND].set(
            jnp.where(hit, 0, out[..., T.W_KIND]))

        # 3. Stamp + store fresh acked sends (sender side :1290-1307).
        e_flags = emitted[..., T.W_FLAGS]
        fresh = (emitted[..., T.W_KIND] != 0) \
            & (e_flags & T.F_ACK_REQUIRED != 0) \
            & (e_flags & T.F_RETRANSMISSION == 0) \
            & (e_flags & T.F_CAUSAL == 0) \
            & (emitted[..., T.W_KIND] != T.MsgKind.ACK)
        rank = jnp.cumsum(fresh, axis=1) - 1
        clocks = ack.next_clock[:, None] + rank
        emitted = emitted.at[..., T.W_CLOCK].set(
            jnp.where(fresh, clocks, emitted[..., T.W_CLOCK]))

        # Store each fresh send into the k-th free slot of the store,
        # where k is the send's order among this round's fresh sends.
        C = cfg.ack_cap
        free = out[..., T.W_KIND] == 0
        free_rank = jnp.cumsum(free, axis=1) - 1
        rows_n = jnp.arange(n)[:, None]
        # slot_of_rank[i, r] = index of node i's r-th free slot (C = none).
        slot_of_rank = jnp.full((n, C), C, jnp.int32)
        slot_of_rank = slot_of_rank.at[
            jnp.broadcast_to(rows_n, free.shape),
            jnp.where(free, free_rank, C)
        ].set(jnp.broadcast_to(
            jnp.arange(C, dtype=jnp.int32)[None, :], free.shape),
            mode="drop")
        n_free = free.sum(axis=1)
        tgt = jnp.take_along_axis(
            slot_of_rank, jnp.clip(rank, 0, C - 1), axis=1)
        store_slot = jnp.where(fresh & (rank < n_free[:, None]), tgt, C)
        out = out.at[
            jnp.broadcast_to(rows_n, store_slot.shape), store_slot
        ].set(emitted, mode="drop")
        # allsum keeps the replicated counter identical across shards.
        overflow = comm.allsum(jnp.sum(
            jnp.maximum(fresh.sum(axis=1) - n_free, 0), dtype=jnp.int32))
        next_clock = ack.next_clock + fresh.sum(axis=1, dtype=jnp.int32)

        # 4. Retransmit tick (pluggable :1421-1470): re-emit the whole
        #    store, flagged.
        refire = ((ctx.rnd + gids) % cfg.retransmit_every == 0) & ctx.alive
        re = out.at[..., T.W_FLAGS].set(
            out[..., T.W_FLAGS] | T.F_RETRANSMISSION)
        re = re.at[..., T.W_KIND].set(
            jnp.where(refire[:, None], out[..., T.W_KIND], 0))
        extra.append(re)

        # Crashed senders freeze their store (their gen_server is dead).
        out = jnp.where(ctx.alive[:, None, None], out, ack.outstanding)
        next_clock = jnp.where(ctx.alive, next_clock, ack.next_clock)
        ack = AckState(outstanding=out, next_clock=next_clock,
                       overflow=ack.overflow + overflow)

    # 5. Causal stamping: pull causal messages off the event lane into
    #    per-lane wide records (emit side, causality_backend :172-201).
    lanes_out = []
    wide_out = []
    for li, lane in enumerate(st.lanes):
        A = cfg.n_actors
        is_c = (emitted[..., T.W_KIND] != 0) \
            & (emitted[..., T.W_FLAGS] & T.F_CAUSAL != 0) \
            & (emitted[..., T.W_LANE] == li)
        # Only actor-resident nodes may send causally.
        actor_ok = (gids < A) & ctx.alive
        is_c = is_c & actor_ok[:, None]

        # The k-th logical message this round gets the clock incremented
        # k+1 times at the sender's own entry.
        n_sent = is_c.sum(axis=1, dtype=vclock.DTYPE)
        rank1 = jnp.cumsum(is_c, axis=1)           # 1-based where is_c
        # Emit-cap overflow drops the TAIL records (slot order), so the
        # clock advances only by the kept prefix — otherwise receivers
        # would wait forever for counters that were never emitted.
        n_kept = jnp.minimum(n_sent, vclock.DTYPE(cfg.causal_emit_cap))
        is_c_all = is_c                       # incl. overflow tail, for
        is_c = is_c & (rank1 <= cfg.causal_emit_cap)  # event-lane removal
        me_actor = jnp.where(gids < A, gids, 0)
        onehot = (jnp.arange(A)[None, :] ==
                  me_actor[:, None]).astype(vclock.DTYPE)
        msg_clocks = lane.clock[:, None, :] + \
            onehot[:, None, :] * rank1[:, :, None].astype(vclock.DTYPE)
        new_clock = lane.clock + onehot * n_kept[:, None]

        wide = jnp.concatenate(
            [emitted, msg_clocks.astype(jnp.int32)], axis=-1)
        packed, _ = _compact(wide, is_c, cfg.causal_emit_cap)
        dropped = jnp.sum(n_sent - n_kept, dtype=jnp.int32)

        # Sender-side loss recovery: history ring + cadenced replay.
        H = cfg.causal_hist_cap
        valid_p = packed[..., T.W_KIND] != 0
        k_idx = jnp.cumsum(valid_p, axis=1) - 1
        pos = jnp.where(valid_p,
                        (lane.hist_ptr[:, None] + k_idx) % H, H)
        rows_n = jnp.broadcast_to(jnp.arange(n)[:, None], pos.shape)
        hist = lane.hist.at[rows_n, pos].set(packed, mode="drop")
        hist_ptr = (lane.hist_ptr
                    + valid_p.sum(axis=1, dtype=jnp.int32)) % H
        refire = ((ctx.rnd + gids) % cfg.retransmit_every == 0) & ctx.alive
        live_slot = refire[:, None] & (hist[..., T.W_KIND] != 0)
        replay = hist.at[..., T.W_FLAGS].set(
            hist[..., T.W_FLAGS] | T.F_RETRANSMISSION)
        # Whole-record zeroing keeps off-actor/idle rows all-zero — the
        # invariant ShardComm.actor_gather's psum reconstruction needs.
        replay = jnp.where(live_slot[..., None], replay, 0)

        wide_out.append(jnp.concatenate([packed, replay], axis=1))
        lanes_out.append(lane._replace(
            clock=jnp.where(ctx.alive[:, None], new_clock, lane.clock),
            hist=jnp.where(ctx.alive[:, None, None], hist, lane.hist),
            hist_ptr=jnp.where(ctx.alive, hist_ptr, lane.hist_ptr),
            overflow=lane.overflow + comm.allsum(dropped)))
        # Remove from the event lane (overflow tail included: it was a
        # causal send, dropped and counted — it must not leak unicast).
        emitted = emitted.at[..., T.W_KIND].set(
            jnp.where(is_c_all, 0, emitted[..., T.W_KIND]))

    # Any message still flagged F_CAUSAL was emitted by a non-actor node
    # or names an unconfigured lane: it must NOT leak onto the unicast
    # path unordered.  Drop it and account for it.
    invalid = jnp.int32(0)
    if st.lanes:
        leak = (emitted[..., T.W_KIND] != 0) & \
            (emitted[..., T.W_FLAGS] & T.F_CAUSAL != 0)
        invalid = comm.allsum(jnp.sum(leak, dtype=jnp.int32))
        emitted = emitted.at[..., T.W_KIND].set(
            jnp.where(leak, 0, emitted[..., T.W_KIND]))

    if extra:
        emitted = jnp.concatenate([emitted] + extra, axis=1)
    return (DeliveryState(ack=ack, lanes=tuple(lanes_out),
                          invalid_causal=st.invalid_causal + invalid),
            emitted, tuple(wide_out))


# ---------------------------------------------------------------------------
# Inbound: dense vectorized causal delivery
# ---------------------------------------------------------------------------

def _fetch(buf: Array, shared: Array, idx: Array) -> Array:
    """Per-node record fetch over the combined candidate index space:
    ``idx < B`` reads the node's buffer row, else the shared table.
    buf [n, B, w], shared [G, w], idx [n, D] -> [n, D, w]."""
    n, B, w = buf.shape
    G = shared.shape[0]
    from_buf = jnp.take_along_axis(
        buf, jnp.clip(idx, 0, B - 1)[..., None], axis=1)
    from_shared = shared[jnp.clip(idx - B, 0, G - 1)]
    out = jnp.where((idx < B)[..., None], from_buf, from_shared)
    return jnp.where((idx < B + G)[..., None], out, 0)


def inbound(cfg: Config, comm, st: DeliveryState, inbox: exchange.Inbox,
            wides: tuple, ctx: RoundCtx
            ) -> tuple[DeliveryState, exchange.Inbox, Array]:
    """Causal receive path: combine this round's records from all actors
    into one shared table, run dense deliverability sweeps for every
    node at once, merge deliveries (in causal order) into the
    model-visible inbox, buffer out-of-order futures.  Also returns the
    global count of causal deliveries this round (for Stats)."""
    W = cfg.msg_words
    A = cfg.n_actors
    B = cfg.causal_buf_cap
    n = comm.n_local
    gids = comm.local_ids()
    rows_n = jnp.arange(n)[:, None]

    n_causal = jnp.int32(0)
    lanes_out = []
    for li, (lane, payload) in enumerate(zip(st.lanes, wides)):
        # Shared candidate table: every actor's records this round.
        shared = comm.actor_gather(payload, A)      # [A, Ec+H, W+A]
        shared = shared.reshape(-1, W + A)
        G = shared.shape[0]
        s_msg, s_clk = shared[:, :W], shared[:, W:].astype(vclock.DTYPE)
        s_src = jnp.minimum(jnp.maximum(s_msg[:, T.W_SRC], 0), A - 1)
        s_cnt = s_clk[jnp.arange(G), s_src]
        s_dep = s_clk.at[jnp.arange(G), s_src].set(0)   # deps w/o sender
        s_valid = s_msg[:, T.W_KIND] != 0

        # Per-receiver transmission faults: each record's arrival at each
        # node rides the (src -> node) edge this round (replays re-ride
        # it next tick — loss is per-transmission, as on a real link).
        cut = faults_mod.edge_cut(
            ctx.faults,
            jnp.broadcast_to(s_msg[None, :, T.W_SRC], (n, G)),
            jnp.where(s_valid[None, :], gids[:, None], -1),
            cfg.seed, ctx.rnd, _CAUSAL_SALT + li)
        arr_ok = s_valid[None, :] & ~cut & ctx.alive[:, None]

        # Buffered candidates (already arrived in earlier rounds).
        b_msg, b_clk = lane.buf[..., :W], \
            lane.buf[..., W:].astype(vclock.DTYPE)
        b_src = jnp.minimum(jnp.maximum(b_msg[..., T.W_SRC], 0), A - 1)
        b_cnt = jnp.take_along_axis(b_clk, b_src[..., None], axis=2)[..., 0]
        b_dep = jnp.where(
            (jnp.arange(A)[None, None, :] == b_src[..., None]), 0, b_clk)
        b_valid = b_msg[..., T.W_KIND] != 0

        clock0 = lane.clock
        INF = jnp.int32(B + G + 1)
        D = min(B + G, cfg.causal_deliver_cap)
        # The per-node quota is bounded by the inbox space actually left
        # after the event lane (and prior lanes) — a record whose clock
        # advance survived but whose payload got cut at the merge would
        # be a silent zero-times delivery.
        free = jnp.maximum(cfg.inbox_cap - inbox.count, 0)
        quota0 = jnp.minimum(jnp.int32(D), free)

        def sweep(carry):
            clock, b_avail, s_avail, quota = carry
            loc_b = jnp.take_along_axis(clock, b_src, axis=1)
            loc_s = clock[:, s_src]                      # [n, G]
            ok_b = b_avail & (b_cnt == loc_b + 1) & \
                jnp.all(b_dep <= clock[:, None, :], axis=2)
            ok_s = s_avail & (s_cnt[None, :] == loc_s + 1) & \
                jnp.all(s_dep[None] <= clock[:, None, :], axis=2)
            # Dedup per (node, sender): lowest combined index wins
            # (buffered records are older -> priority).
            ib = jnp.where(ok_b, jnp.arange(B)[None, :], INF)
            is_ = jnp.where(ok_s, B + jnp.arange(G)[None, :], INF)
            win = jnp.full((n, A), INF, jnp.int32)
            win = win.at[jnp.broadcast_to(rows_n, b_src.shape), b_src
                         ].min(ib)
            win = win.at[jnp.broadcast_to(rows_n, (n, G)),
                         jnp.broadcast_to(s_src[None, :], (n, G))
                         ].min(is_)
            # Delivery quota: the round delivers at most D records per
            # node (the inbox-merge capacity).  Winners beyond the
            # remaining quota stay undelivered — their clocks do NOT
            # advance, so they re-buffer as futures and deliver next
            # round.  Rank winners by index for a deterministic cut.
            rank = jnp.sum((win[:, None, :] < win[:, :, None]), axis=2)
            deliver = (win < INF) & (rank < quota[:, None])
            del_b = ok_b & (ib == jnp.take_along_axis(win, b_src, axis=1)) \
                & jnp.take_along_axis(deliver, b_src, axis=1)
            del_s = ok_s & (is_ == win[:, s_src]) & deliver[:, s_src]
            mb = jnp.max(jnp.where(del_b[..., None], b_clk, 0), axis=1)
            ms = jnp.max(jnp.where(del_s[..., None], s_clk[None], 0),
                         axis=1)
            clock2 = jnp.maximum(clock, jnp.maximum(mb, ms))
            quota2 = quota - jnp.sum(deliver, axis=1, dtype=jnp.int32)
            return (clock2, b_avail & ~del_b, s_avail & ~del_s, quota2), \
                (del_b, del_s)

        b_avail, s_avail = b_valid & ctx.alive[:, None], arr_ok
        clock = clock0
        quota = quota0
        dels = []
        for _ in range(CAUSAL_SWEEPS):
            (clock, b_avail, s_avail, quota), d = sweep(
                (clock, b_avail, s_avail, quota))
            dels.append(d)
        clock_f = jnp.where(ctx.alive[:, None], clock, clock0)

        # Delivery order = (sweep, combined index).
        def order_key(del_list, idx_base, count):
            key = jnp.full((n, count), jnp.int32(2**30))
            for s_i, d in enumerate(del_list):
                k = s_i * (B + G) + idx_base
                key = jnp.minimum(key, jnp.where(d, k, 2**30))
            return key

        key_b = order_key([d[0] for d in dels],
                          jnp.arange(B)[None, :], B)
        key_s = order_key([d[1] for d in dels],
                          B + jnp.arange(G)[None, :], G)
        keys = jnp.concatenate([key_b, key_s], axis=1)     # [n, B+G]
        # top_k of -keys yields the SMALLEST keys first = delivery order;
        # the returned positions ARE combined candidate indices.
        topv, topi = jax.lax.top_k(-keys, D)
        deliv_idx = jnp.where(-topv < 2**30, topi, B + G + 1)
        recs = _fetch(lane.buf, shared, deliv_idx)
        dmsgs = recs[..., :W]
        n_deliv = jnp.sum(keys < 2**30, axis=1, dtype=jnp.int32)
        n_causal = n_causal + comm.allsum(jnp.sum(n_deliv))
        inbox = exchange.merge_inboxes(
            inbox,
            exchange.Inbox(
                data=dmsgs,
                count=jnp.minimum(n_deliv, D),
                drops=jnp.zeros_like(inbox.drops)))

        # Buffer the undelivered futures (stale ones vanish).  Dedup by
        # (sender, counter-offset): replay cycles re-deliver copies of a
        # blocked message every tick — only one copy may occupy a slot
        # (buffered copies, having lower combined index, win).  Offsets
        # beyond B can't deliver before nearer ones fill the buffer, so
        # they're shed and recovered by a later replay.
        loc_bf = jnp.take_along_axis(clock_f, b_src, axis=1)
        off_b = b_cnt.astype(jnp.int32) - loc_bf.astype(jnp.int32)
        off_s = s_cnt[None, :].astype(jnp.int32) - \
            clock_f[:, s_src].astype(jnp.int32)
        fut_b = b_valid & b_avail & (off_b >= 1) & (off_b <= B)
        fut_s = arr_ok & s_avail & (off_s >= 1) & (off_s <= B)
        idx_b = jnp.broadcast_to(jnp.arange(B)[None, :], (n, B))
        idx_s = jnp.broadcast_to(B + jnp.arange(G)[None, :], (n, G))
        tab = jnp.full((n, A, B), INF, jnp.int32)
        tab = tab.at[jnp.broadcast_to(rows_n, (n, B)), b_src,
                     jnp.clip(off_b - 1, 0, B - 1)
                     ].min(jnp.where(fut_b, idx_b, INF))
        tab = tab.at[jnp.broadcast_to(rows_n, (n, G)),
                     jnp.broadcast_to(s_src[None, :], (n, G)),
                     jnp.clip(off_s - 1, 0, B - 1)
                     ].min(jnp.where(fut_s, idx_s, INF))
        keep_b = fut_b & (idx_b == tab[
            jnp.broadcast_to(rows_n, (n, B)), b_src,
            jnp.clip(off_b - 1, 0, B - 1)])
        keep_s = fut_s & (idx_s == tab[
            jnp.broadcast_to(rows_n, (n, G)),
            jnp.broadcast_to(s_src[None, :], (n, G)),
            jnp.clip(off_s - 1, 0, B - 1)])
        fkeys = jnp.concatenate(
            [jnp.where(keep_b, idx_b, INF),
             jnp.where(keep_s, idx_s, INF)], axis=1)
        ftop, fidx = jax.lax.top_k(-fkeys, B)
        keep_idx = jnp.where(-ftop < INF, fidx, B + G + 1)
        new_buf = _fetch(lane.buf, shared, keep_idx)
        n_fut = jnp.sum(fkeys < INF, axis=1, dtype=jnp.int32)
        buf_overflow = comm.allsum(jnp.sum(
            jnp.maximum(n_fut - B, 0), dtype=jnp.int32))

        new_buf = jnp.where(ctx.alive[:, None, None], new_buf, lane.buf)
        lanes_out.append(lane._replace(
            clock=clock_f,
            buf=new_buf,
            overflow=lane.overflow + buf_overflow,
        ))

    return st._replace(lanes=tuple(lanes_out)), inbox, n_causal
