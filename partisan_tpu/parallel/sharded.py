"""Sharded cluster execution over a device mesh.

The node axis is split across devices; one round is a single SPMD program
under ``jax.shard_map``:

- per-node protocol transitions run shard-locally (no communication),
- the event-message exchange and state-gossip merges cross shards with
  one ``all_gather`` over the ``nodes`` mesh axis (ICI), after which each
  shard routes/merges only its own node range — the TPU-native analogue
  of the reference's per-connection TCP fan-out (SURVEY.md §5.8).

``ShardComm`` implements the same interface as ``LocalComm`` (comm.py),
so managers and models run unchanged on 1 or N devices.  Determinism is
placement-invariant because all randomness keys off GLOBAL node ids
(ops/rng.py).

Scaling note: the all-gather volume is O(n_global * emit_cap * msg_words)
per round, which rides ICI comfortably for the target scenarios (100k
nodes x 16 slots x 12 words x 4 B ~ 77 MB/round across the slice); a
sorted all_to_all exchange is the planned optimization once profiles
justify it.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array
from jax.sharding import Mesh, PartitionSpec as P

from partisan_tpu import channels as channels_mod
from partisan_tpu import control as control_mod
from partisan_tpu import delivery as delivery_mod
from partisan_tpu import elastic as elastic_mod
from partisan_tpu import faults as faults_mod
from partisan_tpu import health as health_mod
from partisan_tpu import ingress as ingress_mod
from partisan_tpu import latency as latency_mod
from partisan_tpu import managers as managers_mod
from partisan_tpu import metrics as metrics_mod
from partisan_tpu import provenance as provenance_mod
from partisan_tpu import watchdog as watchdog_mod
from partisan_tpu import workload as workload_mod
from partisan_tpu.cluster import ClusterState, Stats, round_body, run_until
from partisan_tpu.comm import LocalComm
from partisan_tpu.config import Config
from partisan_tpu.ops import exchange, gossip

AXIS = "nodes"


def _shard_map(f, mesh, in_specs, out_specs):
    """jax.shard_map across jax versions: the stable API (>= 0.6, with
    check_vma) when present, else the experimental one (check_rep)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def make_mesh(n_devices: int | None = None) -> Mesh:
    """A 1-D device mesh over the node axis."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (AXIS,))


@dataclasses.dataclass(frozen=True)
class ShardComm:
    """LocalComm interface, executed inside shard_map on one shard."""

    n_global: int
    inbox_cap: int
    msg_words: int
    n_shards: int
    exchange_mode: str = "all_gather"   # Config.sharded_exchange
    a2a_factor: int = 4                 # Config.a2a_factor

    @property
    def n_local(self) -> int:
        return self.n_global // self.n_shards

    @property
    def node_offset(self) -> Array:
        return jax.lax.axis_index(AXIS) * self.n_local

    def local_ids(self) -> Array:
        return self.node_offset + jnp.arange(self.n_local, dtype=jnp.int32)

    def route(self, emitted) -> exchange.Inbox:
        if self.exchange_mode == "all_to_all":
            return self._route_a2a(emitted)
        # [n_local, E, W] -> gather every shard's emissions over ICI, then
        # keep only messages addressed to this shard's node range.
        # Plane-major stacks gather PER PLANE at their narrow storage
        # dtypes (a pytree all_gather) — the int8/int16 planes cut the
        # dominant n_global·E·W wire volume directly (the "ship the wire
        # as packed planes" case; no interleave ever materializes).
        all_emitted = jax.tree.map(
            lambda x: jax.lax.all_gather(x, AXIS, axis=0, tiled=True),
            emitted)
        return exchange.route(all_emitted, self.n_local, self.inbox_cap,
                              node_offset=self.node_offset)

    def _route_a2a(self, emitted) -> exchange.Inbox:
        """Destination-sharded exchange: stable-sort this shard's
        emissions by destination SHARD, pack a fixed per-shard quota,
        ``lax.all_to_all`` over ICI, then route only what arrived.

        Per-shard wire volume is S·Q·W words (Q = a2a_factor·ceil(M/S))
        versus the all_gather's n_global·E·W — at 32k nodes / 8 shards /
        default quota this is an 8/a2a_factor = 2x reduction, growing
        linearly with shard count.  The quota bounds worst-case skew:
        messages beyond it shed (the caller's emitted-vs-delivered stats
        surface the loss).  Stability preserves per-sender FIFO; within
        a destination shard messages from different source shards arrive
        grouped by source — a (shard-id, slot) reorder that per-sender
        FIFO semantics permit (the reference orders only per connection,
        partisan_peer_connections.erl:897-942)."""
        from partisan_tpu.ops import plane as plane_ops
        from partisan_tpu.types import W_DST, W_KIND

        S = self.n_shards
        W = emitted.shape[-1]
        flat = emitted.reshape(-1, W)                    # [M, W]
        M = flat.shape[0]
        Q = min(M, self.a2a_factor * -(-M // S))
        kind = flat[..., W_KIND]
        dst = flat[..., W_DST]
        ok = (kind != 0) & (dst >= 0) & (dst < self.n_global)
        dshard = jnp.where(ok, dst // self.n_local, S)   # sentinel S
        order = jnp.argsort(dshard, stable=True)
        dsh_sorted = dshard[order]
        bounds = jnp.searchsorted(
            dsh_sorted, jnp.arange(S + 1, dtype=dshard.dtype))
        starts = bounds[:-1]                             # [S]
        counts = bounds[1:] - bounds[:-1]                # [S]
        qi = jnp.arange(Q, dtype=jnp.int32)
        pos = jnp.clip(starts[:, None] + qi[None, :], 0, max(M - 1, 0))
        fits = qi[None, :] < counts[:, None]             # [S, Q]
        # ONE destination-shard sort keys every plane's pack; planes ride
        # the all_to_all at their narrow storage dtypes (pytree lowering),
        # so the quota'd per-shard wire volume S·Q·Σdtype_bytes drops by
        # the packing ratio on top of the all_gather->a2a reduction.
        taken = plane_ops.take_records(
            plane_ops.take_records(flat, order), pos)    # [S, Q, W]
        send = plane_ops.where(fits, taken, 0)
        recv = jax.tree.map(
            lambda x: jax.lax.all_to_all(x, AXIS, split_axis=0,
                                         concat_axis=0, tiled=True),
            send)                                        # [S, Q, W]
        return exchange.route(recv.reshape(-1, W), self.n_local,
                              self.inbox_cap, node_offset=self.node_offset)

    def push_max(self, rows: Array, dst: Array) -> Array:
        """Sharded scatter-max gossip WITHOUT replicating the senders:
        each shard scatters its own rows into a full-range proposal,
        shards reduce elementwise (pmax — max is commutative/associative
        so the result is bit-identical to the old gather-everything
        form), and each shard keeps its own node range.  Per-device
        residency is one [n_global, D] proposal instead of the gathered
        [n_global, D] rows + [n_global, K] edges + their [n_global·K, D]
        repeat — for the heartbeat's D=1 rows that is a plain [n]
        vector, which the replicated-node-axis lint rule permits."""
        prop = gossip.push_max(rows, dst, n_out=self.n_global)
        prop = jax.lax.pmax(prop, AXIS)
        return jax.lax.dynamic_slice_in_dim(prop, self.node_offset,
                                            self.n_local, axis=0)

    def push_or(self, rows: Array, dst: Array) -> Array:
        return self.push_max(rows.astype(jnp.uint8), dst).astype(jnp.bool_)

    def allsum(self, x: Array) -> Array:
        """Cross-shard scalar sum (keeps Stats replicated)."""
        return jax.lax.psum(x, AXIS)

    def allmax(self, x: Array) -> Array:
        """Cross-shard scalar max (keeps metrics high-water marks
        replicated — same discipline as allsum)."""
        return jax.lax.pmax(x, AXIS)

    def allmin(self, x: Array) -> Array:
        """Cross-shard elementwise min — the halo-exchange reduction of
        the health plane's segment-local FastSV (each shard's label
        proposals for remote nodes meet here)."""
        return jax.lax.pmin(x, AXIS)

    def gather_vec(self, x: Array) -> Array:
        return jax.lax.all_gather(x, AXIS, axis=0, tiled=True)

    def actor_gather(self, x: Array, a: int) -> Array:
        """Causal actor rows, replicated to every shard.  The actor
        block is shard 0's first ``a`` rows; other shards' slices are
        all-zero (senders are masked by gid < n_actors), so a psum
        reconstructs the block everywhere over ICI."""
        if a > self.n_local:
            raise ValueError(
                f"n_actors={a} must be <= nodes per shard "
                f"({self.n_local}) so the actor block is shard-resident")
        return jax.lax.psum(x[:a], AXIS)


@dataclasses.dataclass
class ShardedCluster:
    """Same API as cluster.Cluster, but the round step is one shard_map'd
    SPMD program over ``mesh``.  State pytrees are sharded on the leading
    node axis; round counter, fault state and stats are replicated."""

    cfg: Config
    mesh: Mesh
    manager: Any = None
    model: Any = None
    interpose: Any = None
    donate: bool = False    # donate the state carry to steps() — same
    #                         contract as Cluster.donate (callers thread
    #                         state linearly)

    def __post_init__(self) -> None:
        if self.manager is None:
            self.manager = managers_mod.get(self.cfg.peer_service_manager)
        from partisan_tpu import interpose as interpose_mod

        self.interpose = interpose_mod.config_delays(self.cfg,
                                                     self.interpose)
        n_shards = self.mesh.devices.size
        if self.cfg.n_nodes % n_shards:
            raise ValueError(
                f"n_nodes={self.cfg.n_nodes} not divisible by "
                f"mesh size {n_shards}")
        self.comm = ShardComm(
            n_global=self.cfg.n_nodes,
            inbox_cap=self.cfg.inbox_cap,
            msg_words=self.cfg.msg_words,
            n_shards=n_shards,
            exchange_mode=self.cfg.sharded_exchange,
            a2a_factor=self.cfg.a2a_factor,
        )
        # Full-size comm used for host-side init / scripting helpers.
        self.host_comm = LocalComm(
            n_global=self.cfg.n_nodes,
            inbox_cap=self.cfg.inbox_cap,
            msg_words=self.cfg.msg_words,
        )
        self._specs = None
        self._step = None

    # ---- sharding specs ----------------------------------------------
    def _state_specs(self, state: ClusterState):
        """PartitionSpecs: node-axis leaves sharded, control state
        replicated."""
        shard = P(AXIS)
        repl = P()

        def spec_like(subtree, s):
            return jax.tree.map(lambda _: s, subtree)

        def delivery_specs(d):
            if d == ():
                return ()
            # Node-axis slabs shard; scalar overflow counters replicate.
            return jax.tree.map(
                lambda x: repl if jnp.ndim(x) == 0 else shard, d)

        return ClusterState(
            rnd=repl,
            faults=spec_like(state.faults, repl),
            inbox=spec_like(state.inbox, shard),
            manager=spec_like(state.manager, shard),
            model=spec_like(state.model, shard),
            delivery=delivery_specs(state.delivery),
            stats=spec_like(state.stats, repl),
            interpose=(self.interpose.specs(shard, repl)
                       if self.interpose is not None else ()),
            outbox=(() if state.outbox == () else jax.tree.map(
                lambda x: repl if jnp.ndim(x) == 0 else shard,
                state.outbox)),
            # Metrics ring: every recorded value is allsum/allmax-reduced
            # before the write, so the ring is identical on every shard.
            metrics=spec_like(state.metrics, repl),
            # Latency histograms: reduced before every accumulate, so
            # replicated like the metrics ring.
            latency=spec_like(state.latency, repl),
            # Flight recorder: the wire capture's node axis (axis 1,
            # behind the ring axis) shards; round labels replicate.
            flight=(() if state.flight == () else latency_mod.FlightState(
                rnd=repl, sent=P(None, AXIS), dropped=P(None, AXIS))),
            # Active prefix width: a scalar operand, replicated like the
            # round counter (every shard masks its own row range off it).
            n_active=(() if isinstance(state.n_active, tuple) else repl),
            # Health ring: snapshots are derived from the all-gathered
            # global graph, so every shard computes identical values —
            # replicated like the metrics ring.
            health=spec_like(state.health, repl),
            # Provenance: the dissemination-forest tables are per-node
            # rows (shard them on the node axis, like the model state
            # they describe); rings/marks/totals are reduced before
            # every write — replicated.
            provenance=(() if state.provenance == ()
                        else provenance_mod.ProvenanceState(
                            parent=shard, hop=shard, claim_rnd=shard,
                            epoch=shard, rnd=repl, dup=repl,
                            gossip=repl, claims=repl, ctl=repl,
                            depth_hwm=repl, cover_rnd=repl,
                            dup_cum=repl, gossip_cum=repl)),
            # Controllers: every decision is a function of already-
            # reduced plane values, so all shards step identical
            # controller state — replicated like the rings it reads.
            control=spec_like(state.control, repl),
            # Traffic generator: a reduced scalar + ring (arrival
            # counts are allsum-reduced before every write), identical
            # on every shard — replicated like the controllers.
            traffic=spec_like(state.traffic, repl),
            # Seed salt: a scalar operand, replicated like n_active
            # (every shard derives the same effective seed from it).
            salt=(() if isinstance(state.salt, tuple) else repl),
            # Elastic resize machinery: drain boundary/deadline and the
            # resize ring are reduced scalars — replicated like the
            # width operand they move.
            elastic=spec_like(state.elastic, repl),
            # Ingress inject buffer: per-node staged requests shard on
            # the node axis like the inbox they feed; the shed/injected
            # ledgers are replicated scalars (allsum-reduced before
            # every write).
            ingress=(() if state.ingress == ()
                     else ingress_mod.IngressState(
                         dst=shard, channel=shard, payload=shard,
                         release=shard, shed_pend=repl,
                         shed_total=repl, injected=repl)),
            # Watchdog invariant plane: every input is an already-
            # reduced plane value and the first-breach latch min-
            # reduces its candidate, so the whole leaf is identical on
            # every shard — replicated like the metrics ring it sits
            # beside.
            watchdog=spec_like(state.watchdog, repl),
        )

    # ---- state construction ------------------------------------------
    def init(self) -> ClusterState:
        return self.shard_state(self._build_init())

    def _build_init(self) -> ClusterState:
        """The UNSHARDED initial state (host/global arrays) — also the
        abstract template ``jax.eval_shape`` traces for the lint
        matrix's sharded programs and the per-device memory census
        (lint/cost.py), so keep it device-placement-free."""
        cfg = self.cfg
        state = ClusterState(
            rnd=jnp.int32(0),
            faults=faults_mod.none(cfg.n_nodes,
                                   cfg.resolved_partition_mode),
            inbox=exchange.empty_inbox(cfg.n_nodes, cfg.inbox_cap,
                                       cfg.wire_layout),
            manager=self.manager.init(cfg, self.host_comm),
            model=self.model.init(cfg, self.host_comm) if self.model is not None else (),
            delivery=(delivery_mod.init(cfg, self.host_comm)
                      if delivery_mod.enabled(cfg) else ()),
            stats=Stats(jnp.int32(0), jnp.int32(0), jnp.int32(0)),
            interpose=(self.interpose.init(cfg, self.host_comm)
                       if self.interpose is not None else ()),
            outbox=(channels_mod.init(cfg, self.host_comm)
                    if channels_mod.enabled(cfg) else ()),
            metrics=(metrics_mod.init(cfg, self.host_comm)
                     if metrics_mod.enabled(cfg) else ()),
            latency=(latency_mod.init(cfg)
                     if latency_mod.enabled(cfg) else ()),
            n_active=(jnp.int32(cfg.n_nodes) if cfg.width_operand
                      else ()),
            health=(health_mod.init(cfg)
                    if health_mod.enabled(cfg) else ()),
            provenance=(provenance_mod.init(cfg, self.host_comm)
                        if provenance_mod.enabled(cfg) else ()),
            control=(control_mod.init(cfg)
                     if control_mod.enabled(cfg) else ()),
            traffic=(workload_mod.init(cfg)
                     if workload_mod.enabled(cfg) else ()),
            salt=(jnp.uint32(0) if cfg.salt_operand else ()),
            elastic=(elastic_mod.init(cfg)
                     if elastic_mod.enabled(cfg) else ()),
            ingress=(ingress_mod.init(cfg, self.host_comm)
                     if ingress_mod.enabled(cfg) else ()),
            watchdog=(watchdog_mod.init(cfg)
                      if watchdog_mod.enabled(cfg) else ()),
        )
        if latency_mod.flight_enabled(cfg):
            # Wire-stack shape discovery by abstract trace (see
            # Cluster.__post_init__): the single-device round body on
            # the global state yields the full (n_global, E, W) stack;
            # shard_state then splits the node axis per the specs.
            tr = jax.eval_shape(
                lambda s: round_body(cfg, self.manager, self.model,
                                     self.host_comm, s,
                                     interpose=self.interpose,
                                     capture=True)[1], state)
            state = state._replace(
                flight=latency_mod.flight_init(cfg,
                                               tuple(tr.sent.shape)))
        return state

    def shard_state(self, state: ClusterState) -> ClusterState:
        """Place a host/global state onto the mesh per the specs."""
        specs = self._state_specs(state)
        return jax.tree.map(
            lambda x, s: jax.device_put(
                x, jax.sharding.NamedSharding(self.mesh, s)),
            state, specs,
        )

    # ---- the sharded round -------------------------------------------
    def _round_shard(self, state: ClusterState) -> ClusterState:
        """Per-shard body under shard_map: the SAME round_body as the
        single-device Cluster, with the shard-aware comm."""
        return round_body(self.cfg, self.manager, self.model, self.comm,
                          state, interpose=self.interpose)

    def _round_shard_traced(self, state: ClusterState):
        return round_body(self.cfg, self.manager, self.model, self.comm,
                          state, interpose=self.interpose, capture=True)

    def _build(self, state: ClusterState) -> None:
        from partisan_tpu.cluster import TraceRound

        specs = self._state_specs(state)
        body = _shard_map(self._round_shard, self.mesh,
                          in_specs=(specs,), out_specs=specs)
        self._round_sharded = body
        self._step = jax.jit(body)
        self._steps = jax.jit(
            lambda s, k: jax.lax.scan(
                lambda c, _: (body(c), None), s, None, length=k)[0],
            static_argnums=1,
            donate_argnums=(0,) if self.donate else ())
        trace_specs = TraceRound(rnd=P(), sent=P(AXIS), dropped=P(AXIS))
        tbody = _shard_map(self._round_shard_traced, self.mesh,
                           in_specs=(specs,),
                           out_specs=(specs, trace_specs))
        self._record = jax.jit(
            lambda s, k: jax.lax.scan(
                lambda c, _: tbody(c), s, None, length=k),
            static_argnums=1)

    # ---- trace recording (Cluster.record parity) ----------------------
    def record(self, state: ClusterState, k: int):
        """Run k sharded rounds capturing the send-path trace — the same
        TraceRound stream as the single-device ``Cluster.record`` (node
        axis gathered across shards), so recorded traces are
        placement-invariant."""
        if self._step is None:
            self._build(state)
        return self._record(state, k)

    # ---- public API ---------------------------------------------------
    def step(self, state: ClusterState) -> ClusterState:
        if self._step is None:
            self._build(state)
        return self._step(state)

    def steps(self, state: ClusterState, k: int) -> ClusterState:
        if self._step is None:
            self._build(state)
        return self._steps(state, k)

    def run_until(self, state, pred, max_rounds: int, check_every: int = 1):
        return run_until(self, state, pred, max_rounds, check_every)
