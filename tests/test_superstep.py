"""Fused supersteps (ISSUE 18 tentpole): ``Config.superstep=R`` folds
R rounds into ONE jitted execution by nesting the round scan — an
outer scan of inner length-R scans plus a same-body remainder scan, so
any k decomposes as k = outer*R + rem with the round body traced once.

Contracts pinned here:

1. **Bit parity** — the fused program is the SAME function: stepping k
   rounds at R=4 equals R=1 bit-for-bit with every observability plane,
   the flight ring and all three in-scan controllers in the carry, for
   R non-divisors of k (the remainder path).  Cadence conds (timers,
   health snapshots, controller reviews) key on the carried ``rnd``,
   so they fire on true round numbers regardless of fusion.
2. **Cap lift under the memory meter** — soak's sizer lifts the
   per-execution round cap to ``chunk_cap * R`` only when the round
   program's materialized-intermediate census clears the pinned
   ``cost_budgets.SUPERSTEP_INTERM_BUDGET_MIB`` (both verdict
   directions tested), quantizing adaptive lengths to ladder multiples
   of R; a >1000-round soak then lands in a SINGLE execution, issuing
   1/8th the dispatches of the unfused engine (the dispatch-count
   meter, via perfwatch).
3. **Crash replay** — a mid-storm worker kill under superstep chunking
   restores and replays bit-identically against the UNFUSED unchunked
   reference: cross-R parity of the whole recovery protocol.

(The O(1)-in-R program-size guard lives in
tests/test_program_budget.py::test_superstep_program_o1.)
"""

import jax

from partisan_tpu import perfwatch, soak
from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config, ControlConfig
from partisan_tpu.models.plumtree import Plumtree

from support import assert_states_bitidentical


def _full_cluster(superstep=1, n=24, seed=3):
    """Every plane + flight ring + all three controllers in the carry."""
    cfg = Config(n_nodes=n, seed=seed, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups",
                 metrics=True, metrics_ring=64, latency=True,
                 health=5, health_ring=32,
                 provenance=True, provenance_ring=64,
                 flight_rounds=4, channel_capacity=True,
                 control=ControlConfig(fanout=True, backpressure=True,
                                       healing=True, ring=16),
                 superstep=superstep)
    return Cluster(cfg, model=Plumtree())


def _booted(cl, settle=20):
    n = cl.cfg.n_nodes
    st = cl.init()
    m = cl.manager.join_many(cl.cfg, st.manager,
                             list(range(1, n)), [0] * (n - 1))
    st = cl.steps(st._replace(manager=m), settle)
    st = st._replace(model=cl.model.broadcast(st.model, 0, 0, int(st.rnd)))
    return cl.steps(st, 5)


def _plain_cluster(superstep=1, n=16, seed=7):
    return Cluster(Config(n_nodes=n, seed=seed, superstep=superstep),
                   model=Plumtree())


def test_superstep_bit_parity_all_planes_controllers():
    """R=4 over k=13 (non-divisor: 3 supersteps + remainder 1) equals
    R=1 bit-for-bit — planes, flight ring and controller leaves
    included, so cadence conds demonstrably fired on true rounds."""
    cl1 = _full_cluster(superstep=1)
    cl4 = _full_cluster(superstep=4)
    st = _booted(cl1)
    ref = cl1.steps(st, 13)
    got = cl4.steps(st, 13)
    assert_states_bitidentical(got, ref, "superstep_r4_k13")


def test_superstep_cap_lift_and_memory_guard(monkeypatch):
    """The sizer's cap lifts to chunk_cap*R only when the census clears
    the pinned budget; adaptive lengths quantize to ladder multiples of
    R; an un-censusable cluster-like never lifts."""
    mk = lambda: _plain_cluster(superstep=8)  # noqa: E731
    eng = soak.Soak(make_cluster=mk)
    assert eng._chunk_cap() == 8 * eng.cfg.chunk_cap     # n=16 clears
    assert eng._cap_info["interm_mib"] \
        <= eng._cap_info["budget_mib"]
    # adaptive sizing: ladder-of-R quantization, capped at the lift
    k = eng._chunk_size(0, 10**9, 0.001, 0)
    assert k % 8 == 0 and k == 8000
    k0 = eng._chunk_size(0, 10**9, None, 0)              # chunk_init path
    assert k0 % 8 == 0
    # budget refused -> the measured-safe cap stands (fresh engine:
    # the verdict is cached per engine)
    from partisan_tpu.lint import cost_budgets
    monkeypatch.setattr(cost_budgets, "SUPERSTEP_INTERM_BUDGET_MIB", 0.0)
    eng2 = soak.Soak(make_cluster=mk)
    assert eng2._chunk_cap() == eng2.cfg.chunk_cap
    assert not eng2._cap_lift

    # a cluster-like the census cannot trace: no lift, no crash
    class Opaque:
        cfg = type("C", (), {"superstep": 8, "n_nodes": 4})()
    monkeypatch.undo()
    eng3 = soak.Soak(make_cluster=Opaque)
    assert eng3._chunk_cap() == eng3.cfg.chunk_cap
    assert "error" in eng3._cap_info


def test_superstep_soak_1200_rounds_single_execution():
    """The dispatch-count meter: at superstep=8 the guarded cap lift
    lands a 1200-round soak in ONE execution (>1000 rounds in a single
    dispatch), while the unfused engine needs 8 — and the two final
    states are bit-identical."""
    cfg = soak.SoakConfig(chunk_cap=150, chunk_fixed=1200,
                          checkpoint_every=1200)
    res1 = soak.Soak(make_cluster=lambda: _plain_cluster(superstep=1),
                     cfg=cfg).run(rounds=1200)
    res8 = soak.Soak(make_cluster=lambda: _plain_cluster(superstep=8),
                     cfg=cfg).run(rounds=1200)
    assert res1.rounds == res8.rounds == 1200
    d1 = perfwatch.decompose_chunks(res1.chunks)
    d8 = perfwatch.decompose_chunks(res8.chunks)
    assert d1["chunks"] == 8 and d8["chunks"] == 1      # <= 1/8th
    assert res8.chunks[0]["k"] == 1200                  # one >1000-round
    #                                                     execution
    lift = [e for e in res8.log if e["kind"] == "superstep_cap"]
    assert lift and lift[0]["lifted"] and lift[0]["chunk_cap"] == 1200
    assert_states_bitidentical(res8.state, res1.state, "superstep_soak")


def test_superstep_mid_storm_kill_restore_replay(tmp_path):
    """Cross-R crash replay: a worker kill mid-storm under superstep=4
    chunking (retry + fresh context + checkpoint restore) must land
    bit-identically on the UNFUSED unchunked storm reference — the
    whole recovery protocol composes with fusion, and replayed rows
    reconcile (sum(k) == rounds run)."""
    mk = lambda: _full_cluster(superstep=4)  # noqa: E731
    cl1 = _full_cluster(superstep=1)
    st = _booted(cl1)
    r0 = int(jax.device_get(st.rnd))
    storm = soak.Storm(events=(
        (0, soak.LinkDrop(0.2)),
        (4, soak.CrashBatch(frac=0.05)),
        (8, soak.Partition()),
        (12, soak.Heal(revive=True)),
        (16, soak.Churn(0.02, 0.02)),
    ), start=r0)
    crashed = {"done": False}

    def step(c, s, k):
        r = int(jax.device_get(s.rnd))
        if not crashed["done"] and r + k > r0 + 25:
            crashed["done"] = True
            raise jax.errors.JaxRuntimeError("injected worker crash")
        return c.steps(s, k)

    eng = soak.Soak(
        make_cluster=mk, storm=storm, step_fn=step,
        cfg=soak.SoakConfig(chunk_fixed=10, cooldown_s=0.0,
                            checkpoint_dir=str(tmp_path),
                            degraded_factor=1e9),
        sleep_fn=lambda s: None)
    res = eng.run(st, rounds=40)
    assert res.retries == 1 and crashed["done"]
    assert sum(row["k"] for row in res.chunks) == res.rounds
    ref = soak.reference_run(cl1, st, r0 + 40, storm=storm)
    assert_states_bitidentical(res.state, ref, "superstep_storm_resume")
