"""The five driver benchmark configs (BASELINE.md "Benchmark configs to
stand up"):

1. 16-node full-mesh + full membership + demers_anti_entropy
2. 1k-node HyParView + demers_rumor_mongering (infection time vs fanout)
3. 10k-node HyParView + Plumtree under 5% link drop (tree repair)
4. 10k-node SCAMP v2 under 30%/min churn (partial-view distribution)
5. 100k-node HyParView + Plumtree + causal broadcast under crash faults

Each scenario returns a metrics dict; ``run_all`` (and the CLI) accepts
a ``scale`` to shrink node counts for CPU smoke runs — the tests run
scaled versions of the same code that produces the TPU numbers.
"""

from __future__ import annotations

import time

import jax
import numpy as np


def _boot_fullmesh(cl, n):
    st = cl.init()
    m = st.manager
    for i in range(1, n):
        m = cl.manager.join(cl.cfg, m, i, 0)
    return cl.steps(st._replace(manager=m), 15)


def _boot_overlay(cl, n, settle=30, waves=4):
    """Batched staggered bootstrap (random contacts) for partial-view
    overlays."""
    rng = np.random.default_rng(7)
    st = cl.init()
    base = 1
    while base < n:
        hi = min(base * waves, n)
        nodes = np.arange(base, hi, dtype=np.int32)
        targets = rng.integers(0, base, size=nodes.shape[0]).astype(np.int32)
        st = st._replace(manager=cl.manager.join_many(
            cl.cfg, st.manager, nodes, targets))
        st = cl.steps(st, 3)
        base = hi
    return cl.steps(st, settle)


def _throughput(cl, st, k=200):
    st = cl.steps(st, k)
    jax.block_until_ready(st)
    t0 = time.perf_counter()
    st = cl.steps(st, k)
    jax.block_until_ready(st)
    return k / (time.perf_counter() - t0)


# ---------------------------------------------------------------------------

def config1_anti_entropy(n=16, max_rounds=120):
    """16-node full-mesh anti-entropy (protocols/demers_anti_entropy.erl):
    rounds to full coverage + simulated rounds/sec."""
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config
    from partisan_tpu.models.anti_entropy import AntiEntropy

    cfg = Config(n_nodes=n, seed=1, inbox_cap=max(32, n + 8))
    model = AntiEntropy()
    cl = Cluster(cfg, model=model)
    st = _boot_fullmesh(cl, n)
    start = int(st.rnd)
    st = st._replace(model=model.broadcast(st.model, 0, 0))
    st, conv = cl.run_until(
        st, lambda s: float(model.coverage(s.model, s.faults.alive, 0)) == 1.0,
        max_rounds)
    return {"config": 1, "n": n, "convergence_rounds": conv - start,
            "rounds_per_sec": round(_throughput(cl, st), 1)}


def config2_rumor(n=1000, max_rounds=200):
    """HyParView + rumor mongering: infection time vs fanout.  Demers
    infect-and-die gossip converges to a coverage FIXED POINT below 1.0
    (~0.80 at k=2 — demers_rumor_mongering.erl semantics); the metric is
    that plateau and the rounds to reach 95% of it."""
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config
    from partisan_tpu.models.rumor_mongering import RumorMongering

    cfg = Config(n_nodes=n, seed=2, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups")
    model = RumorMongering()
    cl = Cluster(cfg, model=model)
    st = _boot_overlay(cl, n)
    start = int(st.rnd)
    st = st._replace(model=model.broadcast(st.model, 0, 0))
    trail = []
    for _ in range(max_rounds // 5):
        st = cl.steps(st, 5)
        cov = float(model.coverage(st.model, st.faults.alive, 0))
        trail.append((int(st.rnd), cov))
        if len(trail) >= 3 and trail[-1][1] == trail[-3][1]:
            break   # plateaued
    plateau = trail[-1][1]
    infection = next(r for (r, c) in trail if c >= 0.95 * plateau) - start
    return {"config": 2, "n": n, "fanout": 2,
            "infection_rounds": infection,
            "coverage_plateau": round(plateau, 4),
            "rounds_per_sec": round(_throughput(cl, st), 1)}


def config3_plumtree_drop(n=10_000, drop=0.05, max_rounds=400):
    """HyParView + Plumtree under iid link drop: the lazy i_have/graft
    repair path must still converge (tree repair,
    partisan_plumtree_broadcast.erl:861-905)."""
    import jax.numpy as jnp

    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config
    from partisan_tpu.models.plumtree import Plumtree

    cfg = Config(n_nodes=n, seed=3, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups")
    model = Plumtree()
    cl = Cluster(cfg, model=model)
    st = _boot_overlay(cl, n)
    st = st._replace(faults=st.faults._replace(link_drop=jnp.float32(drop)))
    start = int(st.rnd)
    st = st._replace(model=model.broadcast(st.model, 0, 0, start))
    st, conv = cl.run_until(
        st, lambda s: float(model.coverage(s.model, s.faults.alive, 0)) == 1.0,
        max_rounds, check_every=10)
    return {"config": 3, "n": n, "link_drop": drop,
            "repair_rounds": (conv - start) if conv >= 0 else -1,
            "rounds_per_sec": round(_throughput(cl, st), 1)}


def config4_scamp_churn(n=10_000, churn_per_min=0.30, rounds=120):
    """SCAMP v2 under churn: partial-view size distribution after a
    sustained birth/death process (self-stabilizes to (c+1)·log n,
    partisan_scamp_v1_membership_strategy.erl:272-276)."""
    import jax.numpy as jnp

    from partisan_tpu import faults as faults_mod
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config

    cfg = Config(n_nodes=n, seed=4, peer_service_manager="scamp_v2",
                 msg_words=16, partition_mode="groups")
    cl = Cluster(cfg)
    st = _boot_overlay(cl, n)
    # churn probability per round (round = 1s of virtual time)
    p = churn_per_min / 60.0
    for _ in range(rounds // 10):
        st = st._replace(faults=faults_mod.churn_step(
            st.faults, cfg.seed, st.rnd, p, p))
        st = cl.steps(st, 10)
    sizes = np.asarray(jnp.sum(st.manager.partial >= 0, axis=1))
    alive = np.asarray(st.faults.alive)
    s = sizes[alive]
    return {"config": 4, "n": n, "churn_per_min": churn_per_min,
            "alive": int(alive.sum()),
            "partial_view_mean": round(float(s.mean()), 2),
            "partial_view_p95": int(np.percentile(s, 95)),
            "expected_c1_logn": round((cfg.scamp.c + 1) * np.log(n), 1),
            "rounds_per_sec": round(_throughput(cl, st), 1)}


def config5_causal_crash(n=100_000, n_actors=16, crashes=16,
                         max_rounds=400):
    """HyParView + Plumtree + causal broadcast under scripted crash
    faults: causal lanes deliver in order while the overlay heals around
    the crashed nodes (the filibuster crash-fault-model shape at the
    north-star scale)."""
    import jax.numpy as jnp

    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import Config
    from partisan_tpu.models.causal_chat import CausalChat
    from partisan_tpu.models.plumtree import Plumtree
    from partisan_tpu.models.stack import Stack

    # Scale-down guards: keep actor/crash counts feasible at smoke sizes.
    n = max(n, 32)
    n_actors = max(4, min(n_actors, n // 4))
    crashes = min(crashes, max(1, (n - n_actors) // 4))

    chat = CausalChat()
    plum = Plumtree()
    stack = Stack([plum, chat])
    cfg = Config(n_nodes=n, seed=5, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups",
                 causal_labels=("default",), n_actors=n_actors)
    cl = Cluster(cfg, model=stack)
    st = _boot_overlay(cl, n)
    # crash a batch of non-actor nodes mid-run (crash fault model)
    rng = np.random.default_rng(11)
    victims = rng.choice(np.arange(n_actors, n), size=crashes, replace=False)
    alive = st.faults.alive
    for v in victims:
        alive = alive.at[int(v)].set(False)
    st = st._replace(faults=st.faults._replace(alive=alive))
    start = int(st.rnd)
    # plumtree broadcast + two causally-chained sends from actors 0, 1
    st = st._replace(model=stack.replace_sub(
        st.model, 0, plum.broadcast(stack.sub(st.model, 0), 0, 0, start)))
    cs = stack.sub(st.model, 1)
    cs = chat.schedule(cs, 0, start + 1)
    # Far enough after that actor 1 has certainly DELIVERED actor 0's
    # broadcast before sending — making the second send causally ordered
    # (not concurrent), so every node must deliver them in order.
    cs = chat.schedule(cs, 1, start + 15)
    st = st._replace(model=stack.replace_sub(st.model, 1, cs))
    st, conv = cl.run_until(
        st, lambda s: float(plum.coverage(stack.sub(s.model, 0),
                                          s.faults.alive, 0)) == 1.0,
        max_rounds, check_every=10)
    st = cl.steps(st, 20)   # let causal deliveries drain
    logs = CausalChat.logs(
        jax.tree.map(lambda x: x[:n_actors], stack.sub(st.model, 1)))
    # Senders don't self-deliver (the reference's causality backend wraps
    # REMOTE sends, partisan_causality_backend.erl:172-201): the ordering
    # property is checked on the receiving actors (2..n_actors).
    ordered = sum(1 for lg in logs[2:] if lg == [1, 1001])
    rps = _throughput(cl, st, k=100)
    wall_estimate = (round((conv - start) / rps, 3) if conv >= 0 else None)
    return {"config": 5, "n": n, "crashes": crashes,
            "convergence_rounds": (conv - start) if conv >= 0 else -1,
            "rounds_per_sec": round(rps, 1),
            "convergence_wall_sec_est": wall_estimate,
            "causal_ordered_actors": ordered,
            "n_receiving_actors": n_actors - 2,
            "n_actors": n_actors}


def config6_echo(n=2, sizes_kb=(1024, 2048, 4096, 8192),
                 concurrency=(1, 2, 4, 8), latencies_ms=(1, 20, 100),
                 parallelism=1, num_messages=1000,
                 bandwidth_mb_s=1000.0, csv_path=None) -> dict:
    """Echo/latency matrix (the reference's ``performance_test`` +
    ``bin/perf-suite.sh`` sweep: SIZE × CONCURRENCY × RTT): two nodes,
    ``concurrency`` ping-pong sender processes sharing the channel's
    ``parallelism`` lanes under capacity enforcement, ``num_messages``
    round trips each.

    Time derivation: one simulated round is one link traversal worth
    ``max(latency/2, size/bandwidth)`` ms (tc-netem delay on loopback +
    serialization at ``bandwidth_mb_s``), so the reported time is
    ``rounds × per_round_ms × 1000`` µs — the same quantity the
    reference's ``timer:tc`` wall-clock captures, minus host scheduling
    noise.  Emits the reference's CSV columns
    ``backend,concurrency,parallelism,bytes,nummessages,latency,time``.
    """
    from partisan_tpu.cluster import Cluster
    from partisan_tpu.config import ChannelSpec, Config, DEFAULT_CHANNEL
    from partisan_tpu.models.echo import CLIENT, Echo

    rows = []
    for conc in concurrency:
        model = Echo(concurrency=conc, num_messages=num_messages)
        cfg = Config(
            n_nodes=n, seed=11, peer_service_manager="static",
            channel_capacity=True, lane_rate=1,
            outbox_cap=max(32, 2 * conc),
            channels=(ChannelSpec(DEFAULT_CHANNEL,
                                  parallelism=parallelism),))
        cl = Cluster(cfg, model=model)
        st0 = cl.init()
        # rounds-to-completion is latency/size-independent (they only
        # scale the virtual clock), so run the ping-pong once per
        # concurrency level and derive every (size, latency) cell.
        st, _ = cl.run_until(
            st0, lambda s: model.done(s.model),
            max_rounds=2 * num_messages
            + 4 * num_messages * conc // max(parallelism, 1) + 50,
            check_every=50)
        assert model.done(st.model), "echo run did not complete"
        rounds = int(st.rnd)
        echoes = int(st.model.echoed[CLIENT].sum())
        assert echoes == conc * num_messages, (echoes, conc)
        for size_kb in sizes_kb:
            for lat in latencies_ms:
                per_round_ms = max(lat / 2.0,
                                   size_kb / 1024.0 / bandwidth_mb_s
                                   * 1000.0)
                time_us = int(rounds * per_round_ms * 1000)
                rows.append({
                    "backend": "partisan_tpu", "concurrency": conc,
                    "parallelism": parallelism,
                    "bytes": size_kb * 1024,
                    "nummessages": num_messages, "latency": lat,
                    "time": time_us, "rounds": rounds,
                })
    if csv_path:
        with open(csv_path, "w") as f:
            f.write("backend,concurrency,parallelism,bytes,"
                    "nummessages,latency,time\n")
            for r in rows:
                f.write(f"{r['backend']},{r['concurrency']},"
                        f"{r['parallelism']},{r['bytes']},"
                        f"{r['nummessages']},{r['latency']},"
                        f"{r['time']}\n")
    return {"config": 6, "cells": len(rows), "rows": rows}


# ---------------------------------------------------------------------------

ALL = {
    1: config1_anti_entropy,
    2: config2_rumor,
    3: config3_plumtree_drop,
    4: config4_scamp_churn,
    5: config5_causal_crash,
    6: config6_echo,
}

DEFAULT_SIZES = {1: 16, 2: 1000, 3: 10_000, 4: 10_000, 5: 100_000, 6: 2}


def run_all(scale: float = 1.0, only=None) -> list[dict]:
    out = []
    for i, fn in ALL.items():
        if only and i not in only:
            continue
        if i == 6:
            out.append(fn(num_messages=max(50, int(1000 * scale))))
            continue
        n = max(8, int(DEFAULT_SIZES[i] * scale))
        out.append(fn(n=n))
    return out


if __name__ == "__main__":
    import argparse
    import json

    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--only", type=int, nargs="*", default=None)
    args = ap.parse_args()
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/partisan_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    for r in run_all(scale=args.scale, only=args.only):
        print(json.dumps(r))
