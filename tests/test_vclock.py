"""Golden-value tests for vclock ops, mirroring the eunit tests embedded in
reference src/partisan_vclock.erl (simple_test/accessor_test/merge tests)."""

import jax.numpy as jnp
import numpy as np

from partisan_tpu.ops import vclock as vc


def clock(*pairs, n=4):
    c = np.zeros(n, np.uint32)
    for actor, count in pairs:
        c[actor] = count
    return jnp.asarray(c)


def test_simple():
    # partisan_vclock.erl simple_test: a=incr(1,fresh), b=incr(2,fresh)
    a = vc.increment(vc.fresh(4), 1)
    b = vc.increment(vc.fresh(4), 2)
    a1, b1 = vc.increment(a, 1), vc.increment(b, 2)
    assert bool(vc.descends(a1, a))
    assert bool(vc.descends(b1, b))
    assert not bool(vc.descends(a1, b1))
    a2 = vc.increment(a1, 1)
    c = vc.merge(a2, b1)
    c1 = vc.increment(c, 3)
    assert bool(vc.descends(c1, a2))
    assert bool(vc.descends(c1, b1))
    assert not bool(vc.descends(b1, c1))
    assert not bool(vc.descends(b1, a1))


def test_accessor():
    # accessor_test: vc = [{1,1},{2,2}]
    v = clock((1, 1), (2, 2))
    assert int(vc.get_counter(v, 1)) == 1
    assert int(vc.get_counter(v, 2)) == 2
    assert int(vc.get_counter(v, 3)) == 0


def test_merge():
    v1 = clock((1, 1), (2, 2), (3, 4))
    v2 = clock((3, 3), (0, 1), n=4)
    merged = vc.merge(v1, v2)
    assert merged.tolist() == [1, 1, 2, 4]


def test_merge_less_left_right():
    # merge_less_left_test / merge_less_right_test
    vl = clock((0, 1), n=3)
    vr = clock((1, 3), (2, 1), n=3)
    assert vc.merge(vl, vr).tolist() == [1, 3, 1]
    assert vc.merge(vr, vl).tolist() == [1, 3, 1]


def test_dominates_and_concurrent():
    a = clock((0, 2), (1, 1))
    b = clock((0, 1), (1, 1))
    assert bool(vc.dominates(a, b))
    assert not bool(vc.dominates(b, a))
    assert not bool(vc.dominates(a, a))
    c = clock((2, 5))
    assert bool(vc.concurrent(a, c))


def test_glb():
    a = clock((0, 2), (1, 1))
    b = clock((0, 1), (2, 9))
    assert vc.glb(a, b).tolist() == [1, 0, 0, 0]


def test_matrix_ops_batch():
    m = vc.fresh_matrix(5, 4)
    m = m.at[0].set(vc.increment(m[0], 2))
    merged = vc.merge(m, m[0])  # broadcast row merge
    assert bool(jnp.all(merged[:, 2] == 1))


def test_deliverable():
    local = clock((0, 3), (1, 1))
    # next message from actor 1:
    good = clock((0, 2), (1, 2))
    assert bool(vc.deliverable(good, local, 1))
    # gap from actor 1:
    gap = clock((1, 3))
    assert not bool(vc.deliverable(gap, local, 1))
    # unsatisfied dep on actor 2:
    dep = clock((1, 2), (2, 1))
    assert not bool(vc.deliverable(dep, local, 1))
