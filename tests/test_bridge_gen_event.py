"""partisan_gen_event semantics OVER THE BRIDGE.

The reference ships a patched OTP gen_event
(priv/otp/24/partisan_gen_event.erl, 1014 LoC) with a conformance suite
(test/partisan_gen_event_SUITE.erl, 1520 LoC).  This suite runs the
PACKAGE manager loop (partisan_tpu.otp.gen_event) over the bridge
transport — only the crash-on-demand handler subclass is suite-local.
~8 representative behaviors at the semantics level:

- add_handler: handlers receive events in ADD order, each with its own
  state,
- notify is fire-and-forget; sync_notify replies after every handler ran,
- call/2 targets ONE handler by id and returns its reply,
- delete_handler stops delivery to that handler only and returns its
  final state,
- a handler that crashes on an event is REMOVED silently; the remaining
  handlers keep running (OTP gen_event isolation),
- swap_handler atomically replaces a handler, seeding the new one with
  the old one's state,
- per-notifier FIFO event ordering.
"""

import pytest

from support import BridgeVM, bridge_rig

from partisan_tpu.otp.gen_event import GenEvent, Handler, Notifier

EV_ADD, EV_CRASH = 1, 99           # event kinds the handlers interpret


class AddHandler(Handler):
    """Accumulates EV_ADD args; crashes on EV_CRASH targeting its id."""

    def handle(self, ev, arg):
        if ev == EV_CRASH and arg == self.id:
            raise RuntimeError(f"handler {self.id} crashed")
        if ev == EV_ADD:
            self.state += arg
        self.events.append(arg)


@pytest.fixture()
def rig():
    srv = bridge_rig(4)
    procs = []
    try:
        mgr = GenEvent(BridgeVM(srv, 0))
        a = Notifier(BridgeVM(srv, 1))
        b = Notifier(BridgeVM(srv, 2))
        procs = [mgr, a, b]
        yield mgr, a, b
    finally:
        for p in procs:
            p.close()
        srv.close()


def _pump(a, mgr, k=3):
    for _ in range(k):
        a.step(1)
        mgr.process()


def test_all_handlers_receive_in_add_order(rig):
    mgr, a, _ = rig
    mgr.add_handler(AddHandler(1))
    mgr.add_handler(AddHandler(2))
    a.notify(mgr.id, EV_ADD, 5)
    _pump(a, mgr)
    assert [h.id for h in mgr.handlers] == [1, 2]
    assert all(h.events == [5] for h in mgr.handlers)
    assert all(h.state == 5 for h in mgr.handlers)


def test_handlers_keep_independent_state(rig):
    mgr, a, _ = rig
    mgr.add_handler(AddHandler(1, state=100))
    mgr.add_handler(AddHandler(2))
    a.notify(mgr.id, EV_ADD, 3)
    _pump(a, mgr)
    assert a.call_handler(mgr, 1) == (True, 103)
    assert a.call_handler(mgr, 2) == (True, 3)


def test_sync_notify_replies_after_handlers_ran(rig):
    mgr, a, _ = rig
    mgr.add_handler(AddHandler(1))
    assert a.sync_notify(mgr, EV_ADD, 7) == (True, 0)
    assert mgr.handlers[0].state == 7     # already applied at reply time


def test_call_targets_one_handler(rig):
    mgr, a, _ = rig
    mgr.add_handler(AddHandler(1, state=11))
    mgr.add_handler(AddHandler(2, state=22))
    assert a.call_handler(mgr, 2) == (True, 22)
    ok, _ = a.call_handler(mgr, 9)        # no such handler
    assert ok is False


def test_delete_handler_stops_delivery_and_returns_state(rig):
    mgr, a, _ = rig
    mgr.add_handler(AddHandler(1))
    mgr.add_handler(AddHandler(2))
    a.notify(mgr.id, EV_ADD, 4)
    _pump(a, mgr)
    assert mgr.delete_handler(1) == 4     # terminate returns final state
    a.notify(mgr.id, EV_ADD, 6)
    _pump(a, mgr)
    assert a.call_handler(mgr, 2) == (True, 10)
    assert a.call_handler(mgr, 1)[0] is False   # deleted: unreachable


def test_crashing_handler_removed_others_survive(rig):
    mgr, a, _ = rig
    mgr.add_handler(AddHandler(1))
    mgr.add_handler(AddHandler(2))
    a.notify(mgr.id, EV_CRASH, 1)         # crashes handler 1 only
    _pump(a, mgr)
    assert [h.id for h in mgr.handlers] == [2]
    a.notify(mgr.id, EV_ADD, 9)
    _pump(a, mgr)
    assert a.call_handler(mgr, 2) == (True, 9)  # survivor still running


def test_swap_handler_preserves_state(rig):
    mgr, a, _ = rig
    mgr.add_handler(AddHandler(1))
    a.notify(mgr.id, EV_ADD, 8)
    _pump(a, mgr)
    assert mgr.swap_handler(1, AddHandler, 3)
    assert a.call_handler(mgr, 3) == (True, 8)  # seeded with old state
    assert a.call_handler(mgr, 1)[0] is False


def test_per_notifier_fifo_ordering(rig):
    mgr, a, _ = rig
    mgr.add_handler(AddHandler(1))
    for arg in (1, 2, 3, 4):
        a.notify(mgr.id, EV_ADD, arg)
    _pump(a, mgr, 6)
    assert mgr.handlers[0].events == [1, 2, 3, 4]
