"""Program-budget guards for the width-generic bootstrap ladder
(ISSUE 3 tentpole; VERDICT r5 next #1).

The r5 bootstrap wall was program LOAD, not simulation: the per-rung
ladder compiled a separate scan program per width (≈90 MB serialized
crossing the relay at ~1.5 MB/s ≈ 45 s).  The fix carries the rung
width as a dynamic ``n_active`` operand (Config.width_operand) so ONE
full-width round program serves every rung.  These tests pin the two
load-bearing contracts on CPU:

1. **Compile count** — the ladder path traces/compiles exactly one
   round-scan program across all rungs (and builds exactly one
   Cluster), so per-bench-size serialized round programs are <= 1.
2. **Prefix dynamics** — a w-prefix run under the width operand is
   bit-identical (state, send-path trace, coverage, convergence round)
   to a natively-``n_nodes=w`` run: ids are global, the hash-RNG
   streams are id-keyed, inert high rows are masked dead on the wire /
   frozen in managers, and every full-range random picker is bounded
   by the operand.  This is the ``_grow_state`` contract, now
   load-bearing for the one-program ladder.
"""

import jax
import numpy as np
import pytest

from partisan_tpu import scenarios
from partisan_tpu.cluster import Cluster, activate, active_alive
from partisan_tpu.config import Config, PlumtreeConfig
from partisan_tpu.models.plumtree import Plumtree


def _cfg(n, width_operand, **kw):
    kw.setdefault("msg_words", 16)
    return Config(n_nodes=n, seed=5, peer_service_manager="hyparview",
                  partition_mode="groups",
                  max_broadcasts=8, inbox_cap=16, timer_stagger=False,
                  width_operand=width_operand,
                  plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4),
                  **kw)


def _drive_waves(cl, width, k_per_wave=10, factor=4):
    """The ladder's wave schedule (same rng discipline) on ``cl``,
    joining nodes [1, width) — activated to ``width`` first when the
    cluster carries the operand."""
    st = cl.init()
    if cl.cfg.width_operand:
        st = activate(st, width)
    rng = np.random.default_rng(7)
    base = 1
    while base < width:
        hi = min(base * factor, width)
        nodes = np.arange(base, hi, dtype=np.int32)
        tgts = rng.integers(0, base, size=nodes.shape[0]).astype(np.int32)
        st = st._replace(manager=cl.manager.join_many(cl.cfg, st.manager,
                                                      nodes, tgts))
        st = cl.steps(st, k_per_wave)
        base = hi
    return cl.steps(st, k_per_wave)


def _prefix_equal(small_tree, big_tree, w_small, w_big, label):
    """Assert every leaf of ``big_tree`` restricted to the node-axis
    prefix equals ``small_tree``'s leaf bit-for-bit."""
    import jax.tree_util as jtu

    ls = jtu.tree_leaves_with_path(small_tree)
    lb = jtu.tree_leaves_with_path(big_tree)
    assert len(ls) == len(lb), (label, len(ls), len(lb))
    for (pa, a), (_pb, b) in zip(ls, lb):
        a = np.asarray(jax.device_get(a))
        b = np.asarray(jax.device_get(b))
        where = label + jtu.keystr(pa)
        if a.shape == b.shape:
            pass
        elif (a.ndim == b.ndim and a.ndim >= 1 and a.shape[0] == w_small
              and b.shape[0] == w_big and a.shape[1:] == b.shape[1:]):
            b = b[:w_small]
        else:
            raise AssertionError(
                f"{where}: unmappable shapes {a.shape} vs {b.shape}")
        assert np.array_equal(a, b), \
            f"{where}: {np.sum(a != b)} of {a.size} elements differ"


def test_ladder_compiles_one_round_program():
    """The width-operand ladder builds ONE cluster and traces ONE
    round-scan program across all rungs — the <=1 serialized round
    program per bench size guard — AND lands the same final state as
    the legacy multi-program ladder (the _grow_state reference
    semantics)."""
    n = 96
    calls = []

    def make_cluster(width, wo=True):
        calls.append(width)
        return Cluster(_cfg(width, wo))

    cl, st = scenarios._boot_ladder(make_cluster, n, widths=[32, 96])
    assert calls == [n], \
        f"width-operand ladder must build one full-width cluster: {calls}"
    # one (state-structure, k) entry in the scan's jit cache = one
    # traced/compiled/serialized round program for the whole ladder
    assert cl._steps._cache_size() == 1, cl._steps._cache_size()
    assert int(st.n_active) == n
    act = np.asarray(jax.device_get(st.manager.active))
    assert float((act.max(axis=1) >= 0).mean()) == 1.0, \
        "every node joined under the one-program ladder"

    # legacy path (width_operand off -> per-rung clusters + _grow_state)
    # must produce the bit-identical final state: prefix activation IS
    # the grow-state re-embedding, done in place
    legacy_calls = []

    def make_legacy(width):
        legacy_calls.append(width)
        return Cluster(_cfg(width, False))

    _, st_legacy = scenarios._boot_ladder(make_legacy, n,
                                          widths=[32, 96])
    assert sorted(set(legacy_calls)) == [32, 96]
    _prefix_equal(st_legacy._replace(n_active=()),
                  st._replace(n_active=()), n, n, "legacy_vs_width_op")


def test_width_operand_prefix_bit_identical():
    """A 32-prefix run of a 64-wide width-operand cluster is
    bit-identical to a native 32-node run: full state AND the recorded
    send-path trace; inert high rows keep their init values."""
    w, n_big = 32, 64
    small = Cluster(_cfg(w, False))
    big = Cluster(_cfg(n_big, True))
    st_s = _drive_waves(small, w)
    st_b = _drive_waves(big, w)

    _prefix_equal(st_s._replace(n_active=()),
                  st_b._replace(n_active=()), w, n_big, "state")

    # inert high rows were never written: bit-equal to a fresh init
    init_b = big.init()
    _prefix_equal(
        jax.tree.map(lambda x: x[w:] if (getattr(x, "ndim", 0) >= 1 and
                                         x.shape[0] == n_big) else x,
                     st_b.manager),
        jax.tree.map(lambda x: x[w:] if (getattr(x, "ndim", 0) >= 1 and
                                         x.shape[0] == n_big) else x,
                     init_b.manager),
        n_big - w, n_big - w, "high_rows")

    # send-path trace parity (the trace-orchestrator record mode):
    # every post-interposition emission and fault drop, per round
    st_s2, tr_s = small.record(st_s, 10)
    st_b2, tr_b = big.record(st_b, 10)
    assert np.array_equal(np.asarray(tr_s.rnd), np.asarray(tr_b.rnd))
    assert np.array_equal(np.asarray(tr_s.sent),
                          np.asarray(tr_b.sent)[:, :w])
    assert np.array_equal(np.asarray(tr_s.dropped),
                          np.asarray(tr_b.dropped)[:, :w])
    # and the high rows emitted NOTHING
    assert int(np.asarray(tr_b.sent)[:, w:, :, 0].max(initial=0)) == 0


def test_width_operand_coverage_and_convergence_parity():
    """Plumtree broadcast over a 48-prefix: coverage series and the
    convergence round match a native 48-node run exactly (the
    trace/coverage/convergence leg of the prefix contract)."""
    w, n_big = 48, 96
    model = Plumtree()
    small = Cluster(_cfg(w, False), model=model)
    big = Cluster(_cfg(n_big, True), model=model)
    st_s = _drive_waves(small, w)
    st_b = _drive_waves(big, w)
    start = int(st_s.rnd)
    assert start == int(st_b.rnd)
    st_s = st_s._replace(model=model.broadcast(st_s.model, 0, 0, start))
    st_b = st_b._replace(model=model.broadcast(st_b.model, 0, 0, start))

    cov_s = jax.jit(lambda s: model.coverage(s.model, s.faults.alive, 0))
    # width-operand coverage MUST mask by the active prefix
    # (cluster.active_alive) — faults.alive alone would count the
    # inert rows as unreached
    cov_b = jax.jit(lambda s: model.coverage(s.model, active_alive(s), 0))

    conv_s = conv_b = -1
    for _ in range(20):
        c_s, c_b = float(cov_s(st_s)), float(cov_b(st_b))
        assert c_s == c_b, (int(st_s.rnd), c_s, c_b)
        if c_s == 1.0:
            conv_s = conv_b = int(st_s.rnd)
            break
        st_s = small.steps(st_s, 10)
        st_b = big.steps(st_b, 10)
    assert conv_s > 0, "broadcast did not converge on the prefix"


def test_width_operand_sharded_parity():
    """The n_active operand is a replicated scalar: the sharded round
    must evolve a width-operand state exactly like the single-device
    round (placement invariance of the mask)."""
    if not hasattr(jax, "shard_map"):
        pytest.skip("jax.shard_map not available in this jax")
    from partisan_tpu.parallel import ShardedCluster, make_mesh

    assert len(jax.devices()) >= 8, "conftest must force 8 cpu devices"
    cfg = _cfg(64, True)
    local = Cluster(cfg)
    shard = ShardedCluster(cfg, make_mesh(8))

    def drive(cl):
        st = activate(cl.init(), 32)
        rng = np.random.default_rng(7)
        base = 1
        while base < 32:
            hi = min(base * 4, 32)
            nodes = np.arange(base, hi, dtype=np.int32)
            tgts = rng.integers(0, base,
                                size=nodes.shape[0]).astype(np.int32)
            st = st._replace(manager=cl.manager.join_many(
                cfg, st.manager, nodes, tgts))
            st = cl.steps(st, 10)
            base = hi
        return cl.steps(st, 10)

    st_l, st_s = drive(local), drive(shard)
    _prefix_equal(st_l, st_s, 64, 64, "sharded")


# ---------------------------------------------------------------------------
# Fusion-regression guard: the one-interleave-per-round budget (ISSUE 6).
# The plane-major pipeline carries message words as a struct of planes
# end to end and ships the exchange as packed planes, so the round
# program contains ZERO plane->wire interleaves (capture mode: exactly
# ONE, for the layout-stable TraceRound.sent).  The legacy interleaved
# layout re-stacks record minors throughout (every msg build + the
# latency/provenance stamps).  Counting at the jaxpr level keeps the
# layout win pinned on CPU between on-chip bench rounds.
#
# The counter itself is the lint package's interleave-budget rule
# (partisan_tpu/lint/rules.py — re-homed there by ISSUE 9, single
# implementation); these tests stay as thin callers pinning the exact
# budgets per program shape.
# ---------------------------------------------------------------------------

from partisan_tpu.lint import count_wire_interleaves  # noqa: E402


def _interleave_counts(cfg, capture=False):
    model = Plumtree()
    cl = Cluster(cfg, model=model)
    st = cl.init()
    fn = cl._round_traced if capture else cl._round
    jaxpr = jax.make_jaxpr(fn)(st).jaxpr
    widths = set(range(cfg.msg_words, cfg.wire_words + 1))
    return count_wire_interleaves(jaxpr, widths)


def test_one_interleave_per_round_budget():
    """Plane-major plain round: ZERO wire interleaves (the exchange
    ships packed planes); capture round: exactly ONE (TraceRound.sent).
    The legacy layout visibly exceeds the budget, so the guard really
    keys on the layout.

    msg_words=17 keeps the guard's width window {17..wire_words}
    disjoint from every other trailing dimension in the round
    (inbox_cap=16 would alias msg_words=16 and false-positive on
    unrelated [n, cap]-trailing transposes)."""
    cfg = _cfg(64, True, msg_words=17)
    n_plain, eq_plain = _interleave_counts(cfg)
    assert n_plain == 0, \
        f"plane-major round traces {n_plain} wire interleaves " \
        f"(budget 0 outside capture; {eq_plain} equations total)"
    n_cap, _ = _interleave_counts(cfg, capture=True)
    assert n_cap == 1, \
        f"capture round must interleave exactly once, got {n_cap}"

    import dataclasses
    legacy = dataclasses.replace(cfg, plane_major=False)
    n_leg, eq_leg = _interleave_counts(legacy)
    assert n_leg > 5, \
        f"legacy layout should re-stack record minors throughout " \
        f"(got {n_leg}; the guard is not keying on the layout)"


def test_one_interleave_budget_with_trailing_words():
    """The budget holds with the latency birth word and provenance pair
    widening the wire (plane-major appends PLANES, never a minor-axis
    concatenate)."""
    cfg = _cfg(64, True, msg_words=17, latency=True, provenance=True)
    n_plain, _ = _interleave_counts(cfg)
    assert n_plain == 0, n_plain


def test_one_interleave_budget_otp_stack():
    """The budget holds for the OTP service stack too (rpc + monitor
    over fullmesh): every record-emitting module must build through the
    layout dispatch, not raw interleaved stacks — a single legacy
    ``msg_ops.build(msg_words, ...)`` call site would show up here as a
    minor-axis concatenate."""
    from partisan_tpu.models.stack import Stack
    from partisan_tpu.otp import monitor as mon_mod
    from partisan_tpu.otp import rpc as rpc_mod

    stack = Stack([rpc_mod.RpcService((lambda x: x + 1,)),
                   mon_mod.MonitorService()])
    cfg = Config(n_nodes=8, seed=13, msg_words=17, inbox_cap=48,
                 timer_stagger=False)
    cl = Cluster(cfg, model=stack)
    st = cl.init()
    jaxpr = jax.make_jaxpr(cl._round)(st).jaxpr
    widths = set(range(cfg.msg_words, cfg.wire_words + 1))
    n_int, _ = count_wire_interleaves(jaxpr, widths)
    assert n_int == 0, \
        f"OTP stack round traces {n_int} wire interleaves (budget 0)"


def test_plane_major_width_operand_cross_parity():
    """Layout x width-operand parity: a 32-prefix run of a PLANE-MAJOR
    width-operand cluster is bit-identical (normalized state + trace)
    to a native 32-node LEGACY-interleaved run — the two layout axes
    compose."""
    from support import assert_states_bitidentical

    w, n_big = 32, 64
    small = Cluster(_cfg(w, False, plane_major=False))
    big = Cluster(_cfg(n_big, True, plane_major=True))
    st_s = _drive_waves(small, w)
    st_b = _drive_waves(big, w)

    import jax.tree_util as jtu
    from support import normalize_wire

    ls = jtu.tree_leaves_with_path(normalize_wire(
        st_s._replace(n_active=())))
    lb = jtu.tree_leaves_with_path(normalize_wire(
        st_b._replace(n_active=())))
    assert len(ls) == len(lb)
    for (pa, a), (_pb, b) in zip(ls, lb):
        a = np.asarray(jax.device_get(a))
        b = np.asarray(jax.device_get(b))
        if a.shape != b.shape and a.ndim == b.ndim and a.ndim >= 1 \
                and a.shape[0] == w and b.shape[0] == n_big:
            b = b[:w]
        assert np.array_equal(a, b), jtu.keystr(pa)

    st_s2, tr_s = small.record(st_s, 8)
    st_b2, tr_b = big.record(st_b, 8)
    assert np.array_equal(np.asarray(tr_s.sent),
                          np.asarray(tr_b.sent)[:, :w])
    assert_states_bitidentical(
        st_s2._replace(n_active=()),
        jax.tree.map(lambda x: x[:w] if (getattr(x, "ndim", 0) >= 1 and
                                         x.shape[0] == n_big) else x,
                     normalize_wire(st_b2._replace(n_active=()))),
        "post_record")


def test_activate_requires_width_operand():
    cl = Cluster(_cfg(16, False))
    st = cl.init()
    with pytest.raises(ValueError, match="width_operand"):
        activate(st, 8)
    # active_alive on a non-operand state is just faults.alive
    assert np.array_equal(np.asarray(active_alive(st)),
                          np.asarray(st.faults.alive))


def test_active_alive_masks_prefix():
    cl = Cluster(_cfg(16, True))
    st = activate(cl.init(), 10)
    m = np.asarray(jax.device_get(active_alive(st)))
    assert m[:10].all() and not m[10:].any()


def test_superstep_program_o1():
    """ISSUE 18 fused supersteps: ``Config.superstep=R`` folds R rounds
    into one jitted execution by nesting the round scan (outer scan of
    inner length-R scans) — the round body traces ONCE and the inner
    jaxpr is shared by reference, so program size is O(1) in R.  Pin
    it: the scan program's recursive eqn census at R=8 equals R=1 up
    to the constant nesting wrapper, and is flat in k."""
    from partisan_tpu.lint.core import iter_eqns

    def eqns_for(superstep, k):
        cl = Cluster(_cfg(16, False, superstep=superstep),
                     model=Plumtree())
        st = jax.eval_shape(cl._build_init)
        jaxpr = jax.make_jaxpr(lambda s: cl._scan(s, k))(st)
        return sum(1 for _ in iter_eqns(jaxpr.jaxpr))

    e1 = eqns_for(1, 8)
    e8 = eqns_for(8, 8)
    e8_long = eqns_for(8, 64)   # 8 supersteps, same single inner body
    assert e8 <= e1 + 8, (e1, e8)
    assert e8_long <= e8 + 8, (e8, e8_long)
