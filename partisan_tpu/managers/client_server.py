"""Client-server (star) peer-service manager.

TPU rebuild of ``partisan_client_server_peer_service_manager``
(reference src/partisan_client_server_peer_service_manager.erl):

- tag-based roles (moduledoc :24-41): the first ``cfg.cs_servers``
  global ids are *servers*, the rest *clients*,
- servers maintain connections with all other servers (full mesh);
  clients connect only to servers; client-client joins are REFUSED
  (``accept_join_with_tag`` :895-903),
- membership is eventually consistent, replicated by gossip (:38-39):
  servers exchange their member bitmaps over server-server edges on the
  periodic tick, and push them to their clients, so every node's
  ``members`` view converges on the full roster,
- sends to unconnected nodes fail, exactly like the reference's
  ``do_send_message`` → ``not_yet_connected`` (:880-892): a client that
  wants another client must route via a server (its ``neighbors`` row
  only ever lists servers, so overlay-driven models do this naturally).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
from jax import Array

from partisan_tpu import faults as faults_mod
from partisan_tpu.comm import LocalComm
from partisan_tpu.config import Config
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.ops import msg as msg_ops

_GOSSIP_EDGE_TAG = 111


class ClientServerState(NamedTuple):
    joined: Array  # bool[n_local, n_global] — established connections
    known: Array   # bool[n_local, n_global] — gossiped membership view


class ClientServer:
    name = "client_server"

    def init(self, cfg: Config, comm: LocalComm) -> ClientServerState:
        n, g = comm.n_local, comm.n_global
        gids = comm.local_ids()
        self_row = jnp.arange(g)[None, :] == gids[:, None]
        return ClientServerState(
            joined=jnp.zeros((n, g), jnp.bool_),
            known=self_row,
        )

    def step(self, cfg: Config, comm: LocalComm, state: ClientServerState,
             ctx: RoundCtx) -> tuple[ClientServerState, Array]:
        n_local, n_global = state.joined.shape
        gids = comm.local_ids()
        all_ids = jnp.arange(n_global, dtype=jnp.int32)

        # Periodic membership gossip along established edges (:38-39).
        fires = ((ctx.rnd + gids) % cfg.gossip_every == 0) & ctx.alive
        dst = jnp.where(fires[:, None] & state.joined,
                        all_ids[None, :], jnp.int32(-1))
        dst = faults_mod.filter_edges(
            ctx.faults, gids, dst, ctx.seed, ctx.rnd, _GOSSIP_EDGE_TAG)
        pushed = comm.push_or(state.known, dst)
        known = state.known | (pushed & ctx.alive[:, None])
        known = jnp.where(ctx.alive[:, None], known, state.known)

        emitted = msg_ops.zero_stack(cfg, (n_local, 0))
        return ClientServerState(joined=state.joined, known=known), emitted

    # ---- views -------------------------------------------------------
    def neighbors(self, cfg: Config, state: ClientServerState,
                  comm: LocalComm | None = None) -> Array:
        n_local, n_global = state.joined.shape
        all_ids = jnp.arange(n_global, dtype=jnp.int32)
        return jnp.where(state.joined, all_ids[None, :], jnp.int32(-1))

    def members(self, cfg: Config, state: ClientServerState,
                comm: LocalComm | None = None) -> Array:
        return state.known

    # ---- scenario scripting (host-side; single-device layout) --------
    def join(self, cfg: Config, state: ClientServerState, node: int,
             target: int) -> ClientServerState:
        """Join refused between two clients (accept_join_with_tag
        :895-903) — the state is returned unchanged, mirroring the
        reference closing the connection."""
        if node >= cfg.cs_servers and target >= cfg.cs_servers:
            return state
        j = state.joined.at[node, target].set(True)
        j = j.at[target, node].set(True)
        k = state.known.at[node, target].set(True)
        k = k.at[target, node].set(True)
        return ClientServerState(joined=j, known=k)

    def leave(self, cfg: Config, state: ClientServerState,
              node: int) -> ClientServerState:
        j = state.joined.at[node, :].set(False)
        j = j.at[:, node].set(False)
        k = state.known.at[:, node].set(False)
        # the leaver resets to its singleton view (a node is always its
        # own member), clearing any stale peers it gossiped with
        k = k.at[node, :].set(False)
        k = k.at[node, node].set(True)
        return ClientServerState(joined=j, known=k)
