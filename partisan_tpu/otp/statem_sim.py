"""In-sim vectorized gen_statem: every node hosts a statem server whose
full event loop — postpone replay in arrival order, state timeouts armed
on entry, event timeouts cancelled by any event — runs ON THE NODE AXIS
inside the jitted round.

This extends the in-sim OTP runtime beyond the counter gen_server
(otp/gen_sim.py): the reference's behaviours are first-class runtime
citizens on every node (priv/otp/24/partisan_gen_statem.erl:1-50), so
the sim backend must be able to run a statem's loop for all nodes at
once, not only through the host-side port machines
(partisan_tpu.otp.gen_statem).

Design — a TABLE machine shared by both runtimes:

:class:`TableStatem` encodes a statem callback module as dense arrays
(``trans``/``reply``/``postpone``/``event_timeout`` over [state, event],
``state_timeout`` over [state]).  The same instance serves as

- a host-side :class:`partisan_tpu.otp.gen_statem.Module` (it implements
  ``handle_event``/``state_timeout``), driven by the sequential
  :class:`~partisan_tpu.otp.gen_statem.GenStatem` loop over any port, and
- the interpretation tables for :class:`StatemService`, whose round step
  replays the identical loop as a ``lax.scan`` of micro-steps over a per-
  node event ring — which is what makes conformance checkable on
  identical schedules (tests/test_statem_sim.py).

Loop semantics transposed (mirroring gen_statem.py, which documents the
reference anchors):

1. the round's queue is [state-timer, event-timer?, external events in
   arrival order]; the event-timer entry exists only when no external
   event arrived (the reference cancels the event timeout the moment the
   queue is non-empty),
2. each micro-step consumes the queue head: external events cancel a
   pending event timeout; a postponed event appends to the postpone
   buffer; a handled call replies from the PRE-transition state; a state
   change re-arms the state timeout and PREPENDS the postponed buffer
   (original arrival order) ahead of the unprocessed remainder,
3. timers fire as internal events through the same tables (internal
   columns ignore postpone/reply, the _dispatch_internal contract).

The queue is a ring (int arithmetic on a head index), so the prepend is
O(postpone_cap) scatters, and every micro-step is a handful of [n]
vector ops — the whole cluster's statems advance together.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from partisan_tpu import types as T
from partisan_tpu.comm import LocalComm
from partisan_tpu.config import Config
from partisan_tpu.managers.base import RoundCtx
from partisan_tpu.ops import msg as msg_ops
from partisan_tpu.ops import plane as plane_ops
from partisan_tpu.otp import client as client_mod
from partisan_tpu.otp import gen_statem as host_statem

# queue-entry types
_ENT_EVENT, _ENT_CALL, _ENT_ST_TIMER, _ENT_EV_TIMER = 0, 1, 2, 3


class TableStatem:
    """A statem callback module as dense transition tables.

    ``n_states`` x ``n_events`` externals plus two internal columns
    (state timeout, event timeout).  ``trans`` -1 = keep_state;
    ``reply`` -1 = no reply, else the call replies ``reply + arg``;
    ``event_timeout``/``state_timeout`` -1 = don't arm.
    """

    def __init__(self, n_states: int, n_events: int, init_state: int,
                 trans, reply, postpone, event_timeout,
                 state_timeout) -> None:
        self.n_states, self.n_events = n_states, n_events
        self.init_state = init_state
        ncol = n_events + 2
        self.trans = np.asarray(trans, np.int32).reshape(n_states, ncol)
        self.reply = np.asarray(reply, np.int32).reshape(n_states, ncol)
        self.postpone = np.asarray(postpone, bool).reshape(n_states, ncol)
        self.event_timeout = np.asarray(
            event_timeout, np.int32).reshape(n_states, ncol)
        self.state_timeout_tbl = np.asarray(state_timeout,
                                            np.int32).reshape(n_states)

    # -- host-side Module protocol (gen_statem.Module) ------------------
    def _col(self, ev: int) -> int:
        if ev == host_statem.EV_STATE_TIMEOUT:
            return self.n_events
        if ev == host_statem.EV_EVENT_TIMEOUT:
            return self.n_events + 1
        return min(max(int(ev), 0), self.n_events - 1)

    def handle_event(self, state: int, ev: int, arg: int,
                     is_call: bool) -> host_statem.Result:
        c = self._col(ev)
        nxt = int(self.trans[state, c])
        rep = int(self.reply[state, c])
        evt = int(self.event_timeout[state, c])
        return host_statem.Result(
            next_state=None if nxt < 0 else nxt,
            reply=None if rep < 0 else rep + int(arg),
            postpone=bool(self.postpone[state, c]),
            event_timeout=None if evt < 0 else evt)

    def state_timeout(self, state: int) -> Optional[int]:
        t = int(self.state_timeout_tbl[state])
        return None if t < 0 else t


class StatemSimState(NamedTuple):
    # server side (one statem per node)
    sm: Array         # int32[n] — current state
    started: Array    # bool[n] — initial state_timeout armed
    st_dl: Array      # int32[n] — state-timeout deadline (-1 = none)
    ev_dl: Array      # int32[n] — event-timeout deadline (-1 = none)
    post: Array       # int32[n, P, 5] — postponed (typ, src, ev, arg, ref)
    pcount: Array     # int32[n]
    unprocessed: Array  # int32[n] — faithfulness violations: events
    #                     still queued when the micro-step budget ran
    #                     out, PLUS events that should have postponed
    #                     but overflowed the postpone buffer (they
    #                     dispatch instead of replaying — the host loop
    #                     postpones unboundedly).  MUST stay 0 for the
    #                     loop to conform; a nonzero count means the
    #                     static bounds were undersized for the traffic
    #                     — detectable, never silent.
    # caller side (per-node call table, the gen_sim vocabulary)
    status: Array     # int32[n, C]
    dst: Array        # int32[n, C]
    ev: Array         # int32[n, C]
    arg: Array        # int32[n, C]
    ref: Array        # int32[n, C]
    deadline: Array   # int32[n, C]
    result: Array     # int32[n, C]
    next_ref: Array   # int32[n]


class StatemService:
    """Stackable model: one table statem per node + its call client.

    ``micro_steps`` bounds the per-round event loop.  The worst case is
    E*(P+1)+2 micro-steps for E external events in one round (every
    event postponed and replayed on every transition); the default
    covers E = cap (one full caller table aimed at one server) with the
    default postpone_cap.  If the budget ever runs out anyway, the
    shortfall lands in ``unprocessed`` — a loud conformance break, not
    a silent drop (checked by tests/test_statem_sim.py).
    """

    name = "gen_statem"

    def __init__(self, module: TableStatem, cap: int = 8,
                 postpone_cap: int = 4,
                 micro_steps: int | None = None) -> None:
        self.module = module
        self.cap = cap
        self.postpone_cap = postpone_cap
        self.micro_steps = micro_steps if micro_steps is not None \
            else cap * (postpone_cap + 1) + 2

    def init(self, cfg: Config, comm: LocalComm) -> StatemSimState:
        n, c, p = comm.n_local, self.cap, self.postpone_cap
        zi = jnp.zeros((n, c), jnp.int32)
        return StatemSimState(
            sm=jnp.full((n,), self.module.init_state, jnp.int32),
            started=jnp.zeros((n,), jnp.bool_),
            st_dl=jnp.full((n,), -1, jnp.int32),
            ev_dl=jnp.full((n,), -1, jnp.int32),
            post=jnp.zeros((n, p, 5), jnp.int32),
            pcount=jnp.zeros((n,), jnp.int32),
            unprocessed=jnp.zeros((n,), jnp.int32),
            status=zi, dst=zi, ev=zi, arg=zi, ref=zi, deadline=zi,
            result=zi, next_ref=jnp.ones((n,), jnp.int32))

    # ------------------------------------------------------------------
    def step(self, cfg: Config, comm: LocalComm, st: StatemSimState,
             ctx: RoundCtx, nbrs: Array) -> tuple[StatemSimState, Array]:
        n = st.sm.shape[0]
        P = self.postpone_cap
        gids = comm.local_ids()
        alive = ctx.alive
        rnd = ctx.rnd
        inb = ctx.inbox.data
        cap = inb.shape[1]
        NE = self.module.n_events
        trans = jnp.asarray(self.module.trans)
        reply_t = jnp.asarray(self.module.reply)
        post_t = jnp.asarray(self.module.postpone)
        evtmo_t = jnp.asarray(self.module.event_timeout)
        sttmo_t = jnp.asarray(self.module.state_timeout_tbl)
        rows = jnp.arange(n, dtype=jnp.int32)

        # ---- first step: entering the INITIAL state arms its timer ----
        fresh = alive & ~st.started
        t0 = sttmo_t[st.sm]
        st_dl = jnp.where(fresh & (t0 >= 0), rnd + t0, st.st_dl)
        started = st.started | alive

        # ---- build the round's queue ----------------------------------
        m_call = (inb[..., T.W_KIND] == T.MsgKind.GEN_CALL) & alive[:, None]
        m_ev = (inb[..., T.W_KIND] == T.MsgKind.GEN_CAST) & alive[:, None]
        valid = m_call | m_ev                                   # [n, cap]
        had_ext = valid.any(axis=1)
        entry = jnp.stack([
            jnp.where(m_call, _ENT_CALL, _ENT_EVENT),
            inb[..., T.W_SRC], inb[..., T.P0], inb[..., T.P1],
            inb[..., T.P2]], axis=-1)                           # [n, cap, 5]
        LQ = cap + P + 4
        # ring slots 0/1 = timers; externals compact to 2.. in inbox
        # (= arrival) order
        queue = jnp.zeros((n, LQ, 5), jnp.int32)
        queue = queue.at[:, 0, 0].set(_ENT_ST_TIMER)
        queue = queue.at[:, 1, 0].set(_ENT_EV_TIMER)
        pos = 2 + jnp.cumsum(valid, axis=1) - valid
        r2 = jnp.broadcast_to(rows[:, None], (n, cap))
        queue = queue.at[r2, jnp.where(valid, pos, LQ)].set(
            entry, mode="drop")
        count = 2 + jnp.sum(valid, axis=1, dtype=jnp.int32)

        Rm = cap + 2
        carry = (st.sm, st_dl, st.ev_dl, jnp.zeros((n,), jnp.int32),
                 count, queue, st.post, st.pcount,
                 jnp.zeros((n, Rm, 3), jnp.int32),
                 jnp.zeros((n,), jnp.int32), jnp.zeros((n,), jnp.int32))

        def micro(c, _):
            sm, sdl, edl, head, cnt, q, po, pc, reps, rc, ovf = c
            active = (cnt > 0) & alive
            e = q[rows, jnp.where(active, head % LQ, 0)]        # [n, 5]
            typ, esrc, eev, earg, eref = jnp.unstack(e, axis=-1)
            is_ext = active & (typ <= _ENT_CALL)
            st_fire = active & (typ == _ENT_ST_TIMER) \
                & (sdl >= 0) & (rnd >= sdl)
            ev_fire = active & (typ == _ENT_EV_TIMER) \
                & (edl >= 0) & (rnd >= edl) & ~had_ext
            # consuming any external event cancels a pending event
            # timeout (including one armed earlier this same batch)
            edl = jnp.where(is_ext | ev_fire, -1, edl)
            sdl = jnp.where(st_fire, -1, sdl)
            col = jnp.where(st_fire, NE,
                            jnp.where(ev_fire, NE + 1,
                                      jnp.clip(eev, 0, NE - 1)))
            nxt = trans[sm, col]
            rep = reply_t[sm, col]
            evt = evtmo_t[sm, col]
            wants_pp = is_ext & post_t[sm, col]
            do_pp = wants_pp & (pc < P)
            # overflow: the host loop postpones unboundedly; dispatching
            # instead is a conformance break — count it, never silent
            ovf = ovf + (wants_pp & ~do_pp)
            handled = (is_ext & ~do_pp) | st_fire | ev_fire
            # postpone: append in arrival order
            po = po.at[rows, jnp.where(do_pp, pc, P)].set(e, mode="drop")
            pc = pc + do_pp
            # reply from the PRE-transition state
            do_rep = handled & (typ == _ENT_CALL) & (rep >= 0) \
                & (eref > 0)
            reps = reps.at[rows, jnp.where(do_rep, rc, Rm)].set(
                jnp.stack([esrc, rep + earg, eref], -1), mode="drop")
            rc = rc + do_rep
            # event-timeout arm rides the action
            edl = jnp.where(handled & (evt >= 0), rnd + evt, edl)
            # transition: re-arm state timeout, replay postponed
            changed = handled & (nxt >= 0) & (nxt != sm)
            sm = jnp.where(handled & (nxt >= 0), nxt, sm)
            tn = sttmo_t[sm]
            sdl = jnp.where(changed, jnp.where(tn >= 0, rnd + tn, -1), sdl)
            h2 = head + 1
            npp = jnp.where(changed, pc, 0)
            for i in range(P):
                take = changed & (i < pc)
                qpos = (h2 - npp + i) % LQ
                q = q.at[rows, jnp.where(take, qpos, LQ)].set(
                    po[:, i], mode="drop")
            head = jnp.where(active, h2 - npp, head)
            cnt = jnp.where(active, cnt - 1 + npp, cnt)
            pc = jnp.where(changed, 0, pc)
            return (sm, sdl, edl, head, cnt, q, po, pc, reps, rc,
                    ovf), None

        carry, _ = jax.lax.scan(micro, carry, None,
                                length=self.micro_steps)
        (sm, st_dl, ev_dl, _, leftover, _, post, pcount, reps, rc,
         ovf) = carry

        resp = msg_ops.build(
            cfg, T.MsgKind.GEN_REPLY, gids[:, None],
            jnp.where(jnp.arange(Rm)[None, :] < rc[:, None],
                      reps[..., 0], -1),
            payload=(reps[..., 1], reps[..., 2]))

        # ---- caller side: the shared gen call client -------------------
        status, result, req = client_mod.client_round(
            cfg, comm, ctx, status=st.status, dst=st.dst, a=st.ev,
            b=st.arg, ref=st.ref, deadline=st.deadline, result=st.result)

        out = st._replace(
            sm=jnp.where(alive, sm, st.sm),
            started=started,
            st_dl=jnp.where(alive, st_dl, st.st_dl),
            ev_dl=jnp.where(alive, ev_dl, st.ev_dl),
            post=jnp.where(alive[:, None, None], post, st.post),
            pcount=jnp.where(alive, pcount, st.pcount),
            unprocessed=st.unprocessed
            + jnp.where(alive, leftover + ovf, 0),
            status=status, result=result)
        return out, plane_ops.concat([resp, req], axis=1)

    # ---- host-side API ------------------------------------------------
    def call(self, st: StatemSimState, caller: int, dst: int, ev: int,
             arg: int, timeout_rounds: int, now: int
             ) -> tuple[StatemSimState, int]:
        ref = int(st.next_ref[caller])
        st = client_mod.alloc(st, caller, dst=dst, ev=ev, arg=arg,
                              ref=ref, deadline=now + timeout_rounds,
                              result=0)
        return st._replace(next_ref=st.next_ref.at[caller].add(1)), ref

    def event(self, st: StatemSimState, caller: int, dst: int, ev: int,
              arg: int = 0) -> StatemSimState:
        """Fire-and-forget statem event (gen_statem:cast)."""
        return client_mod.alloc(st, caller, dst=dst, ev=ev, arg=arg,
                                ref=0, deadline=0, result=0)

    def response(self, st: StatemSimState, caller: int, ref: int
                 ) -> tuple[str, int | None]:
        return client_mod.response(st, caller, ref)

    def free(self, st: StatemSimState, caller: int, ref: int
             ) -> StatemSimState:
        return client_mod.free(st, caller, ref)
