"""Fault-hash determinism and boundary tests."""

import jax.numpy as jnp

from partisan_tpu import faults as faults_mod
from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config
from partisan_tpu.models.anti_entropy import AntiEntropy


def test_hash_bernoulli_boundaries():
    h = faults_mod.edge_hash(
        0, jnp.int32(3), 7,
        jnp.arange(4096, dtype=jnp.int32),
        jnp.arange(4096, dtype=jnp.int32)[::-1])
    assert bool(jnp.all(faults_mod.hash_bernoulli(h, 1.0)))
    assert not bool(jnp.any(faults_mod.hash_bernoulli(h, 0.0)))
    frac = float(jnp.mean(faults_mod.hash_bernoulli(h, 0.3)))
    assert abs(frac - 0.3) < 0.05, frac


def test_edge_hash_decorrelated_across_rounds():
    """Edges must not keep identical fates forever (the cascade-mix fix):
    over many rounds, two fixed distinct edges agree ~50% of the time for
    p=0.5, not 100%."""
    rounds = jnp.arange(512, dtype=jnp.int32)
    h1 = faults_mod.edge_hash(0, rounds, 7, jnp.int32(3), jnp.int32(5))
    h2 = faults_mod.edge_hash(0, rounds, 7, jnp.int32(5), jnp.int32(3))
    d1 = faults_mod.hash_bernoulli(h1, 0.5)
    d2 = faults_mod.hash_bernoulli(h2, 0.5)
    agree = float(jnp.mean(d1 == d2))
    assert 0.3 < agree < 0.7, agree


def test_total_link_drop_blocks_everything():
    cfg = Config(n_nodes=8, seed=2)
    model = AntiEntropy()
    cl = Cluster(cfg, model=model)
    st = cl.init()
    for i in range(1, 8):
        st = st._replace(manager=cl.manager.join(cfg, st.manager, i, 0))
    st = st._replace(
        faults=st.faults._replace(link_drop=jnp.float32(1.0)),
        model=model.broadcast(st.model, 0, 0),
    )
    st = cl.steps(st, 40)
    # Nothing crosses a fully lossy network: no deliveries, no spread.
    assert int(st.stats.delivered) == 0
    assert float(model.coverage(st.model, st.faults.alive, 0)) == 1 / 8
    m = cl.manager.members(cfg, st.manager)
    assert int(jnp.sum(m)) == 8 + 7  # self-knowledge + the join targets only


def test_groups_partition_mode():
    """O(n) groups representation: full splits work, partial cuts raise
    (no silent semantics change when 'auto' switches at scale)."""
    import pytest
    from partisan_tpu import faults as faults_mod

    f = faults_mod.none(8, partition_mode="groups")
    assert f.partition.shape == (8,)
    f2 = faults_mod.inject_partition(f, [0, 1, 2, 3], [4, 5, 6, 7])
    import jax.numpy as jnp
    cut = faults_mod.edge_cut(f2, jnp.int32(0), jnp.int32(4), 0,
                              jnp.int32(0), 1)
    same = faults_mod.edge_cut(f2, jnp.int32(4), jnp.int32(5), 0,
                               jnp.int32(0), 1)
    assert bool(cut) and not bool(same)
    healed = faults_mod.resolve_partition(f2)
    assert not bool(faults_mod.edge_cut(healed, jnp.int32(0), jnp.int32(4),
                                        0, jnp.int32(0), 1))
    with pytest.raises(ValueError):
        faults_mod.inject_partition(f, [0], [4])      # partial cut
    with pytest.raises(ValueError):
        faults_mod.inject_partition(f, [0, 4], [4, 1, 2, 3, 5, 6, 7])  # overlap


def test_groups_partition_composes_as_refinement():
    """Two sequential full splits cut the UNION of both edge sets: after
    {0,1}|{2,3} then {0,2}|{1,3}, every pair is cut (4 singleton
    groups) — a naive max+1 reassignment would silently reconnect 1-3."""
    import itertools

    import jax.numpy as jnp
    from partisan_tpu import faults as faults_mod

    f = faults_mod.none(4, partition_mode="groups")
    f = faults_mod.inject_partition(f, [0, 1], [2, 3])
    f = faults_mod.inject_partition(f, [0, 2], [1, 3])
    for a, b in itertools.combinations(range(4), 2):
        assert bool(faults_mod.edge_cut(
            f, jnp.int32(a), jnp.int32(b), 0, jnp.int32(0), 1)), (a, b)
    healed = faults_mod.resolve_partition(f)
    assert not bool(faults_mod.edge_cut(
        healed, jnp.int32(1), jnp.int32(3), 0, jnp.int32(0), 1))


def test_fast_wire_path_matches_generic():
    """The fused wire stage (cluster.round_body fast path: ONE packed
    gather for shed + partition/crash/omission masks) must evolve the
    cluster BIT-IDENTICALLY to the generic multi-gather composition —
    same hash stream, same shed decisions, same stats.  A no-op Observe
    interposition forces the generic path on an otherwise identical
    configuration, under simultaneous crashes + a groups partition +
    iid link drop + monotonic backpressure traffic."""
    import jax

    from partisan_tpu import interpose
    from partisan_tpu.config import HyParViewConfig, PlumtreeConfig
    from partisan_tpu.models.plumtree import Plumtree

    def make(force_generic):
        cfg = Config(n_nodes=96, seed=5, peer_service_manager="hyparview",
                     msg_words=16, partition_mode="groups",
                     max_broadcasts=4, inbox_cap=8,
                     hyparview=HyParViewConfig(),
                     plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4))
        probe = interpose.Observe(
            fn=lambda c, x, em: jnp.int32(0),
            combine=lambda s, a: s) if force_generic else None
        return Cluster(cfg, model=Plumtree(), interpose=probe)

    def drive(cl):
        st = cl.init()
        m = cl.manager.join_many(
            cl.cfg, st.manager, list(range(1, 96)), [0] * 95)
        st = cl.steps(st._replace(manager=m), 20)
        st = st._replace(model=cl.model.broadcast(st.model, 0, 0, 7))
        # crashes + partition + link drop, all at once
        alive = st.faults.alive.at[jnp.asarray([5, 17, 33])].set(False)
        part = st.faults.partition.at[jnp.arange(48)].set(1)
        st = st._replace(faults=st.faults._replace(
            alive=alive, partition=part,
            link_drop=jnp.float32(0.15)))
        return cl.steps(st, 25)

    fast = drive(make(False))
    slow = drive(make(True))
    # the interpose leaf itself differs ((), Observe counter); every
    # other component of the cluster state must not
    assert int(fast.stats.emitted) == int(slow.stats.emitted)
    assert int(fast.stats.delivered) == int(slow.stats.delivered)
    assert int(fast.stats.dropped) == int(slow.stats.dropped)
    for name in ("rnd", "inbox", "manager", "model", "faults"):
        fa = jax.tree.leaves(getattr(fast, name))
        sl = jax.tree.leaves(getattr(slow, name))
        assert len(fa) == len(sl)
        for x, y in zip(fa, sl):
            assert bool(jnp.array_equal(x, y)), name


def test_fast_wire_compaction_overflow_characterization():
    """ADVICE r5 #1: the fast wire path compacts the emission stack
    BEFORE shed/fault filtering (the documented ordering divergence,
    cluster.round_body), so a fault-cut message still occupies a
    compacted slot.  When a node's live emissions exceed ``emit_compact``
    in a faulted round, the loss shifts from the fault counter to the
    compaction counter and the delivered set shrinks vs the generic
    path (which filters first, compacts after).  This characterizes ONE
    divergent round from an identical state, asserting the documented
    drop-counter delta — so the divergence stays bounded and
    intentional, not silent."""
    from partisan_tpu import interpose
    from partisan_tpu import metrics as metrics_mod
    from partisan_tpu.config import PlumtreeConfig
    from partisan_tpu.models.plumtree import Plumtree

    def make(force_generic):
        cfg = Config(n_nodes=96, seed=6, peer_service_manager="hyparview",
                     msg_words=16, partition_mode="groups",
                     max_broadcasts=4, inbox_cap=8,
                     emit_compact=4,      # small enough to overflow
                     # seed re-tuned when the rank32 stream changed
                     # (single-pass finalizer): the characterization
                     # needs a round whose live emissions overflow
                     # emit_compact under faults
                     metrics=True, metrics_ring=8,
                     plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4))
        probe = interpose.Observe(
            fn=lambda c, x, em: jnp.int32(0),
            combine=lambda s, a: s) if force_generic else None
        return Cluster(cfg, model=Plumtree(), interpose=probe)

    fast, gen = make(False), make(True)
    st = fast.init()
    m = fast.manager.join_many(
        fast.cfg, st.manager, list(range(1, 96)), [0] * 95)
    st = fast.steps(st._replace(manager=m), 20)
    st = st._replace(model=fast.model.broadcast(st.model, 0, 0, 7))
    alive = st.faults.alive.at[jnp.asarray([5, 17, 33])].set(False)
    st = st._replace(faults=st.faults._replace(
        alive=alive, link_drop=jnp.float32(0.15)))

    # ONE round from the SAME state on both paths (only the interpose
    # leaf differs structurally).
    f1 = fast.step(st)
    g1 = gen.step(st._replace(
        interpose=gen.interpose.init(gen.cfg, gen.comm)))

    de_f = int(f1.stats.emitted - st.stats.emitted)
    de_g = int(g1.stats.emitted - st.stats.emitted)
    dd_f = int(f1.stats.delivered - st.stats.delivered)
    dd_g = int(g1.stats.delivered - st.stats.delivered)
    dr_f = int(f1.stats.dropped - st.stats.dropped)
    dr_g = int(g1.stats.dropped - st.stats.dropped)

    # Emission counting is identical (both count the pre-wire stack
    # minus sheds); the divergence is WHERE messages die downstream.
    assert de_f == de_g
    # Fault-cut messages occupying compacted slots push live messages
    # out: the fast path delivers a subset — strictly fewer here (the
    # scenario is tuned so live emissions exceed emit_compact under
    # faults; if this stops overflowing, the characterization is dead).
    assert dd_f < dd_g, (dd_f, dd_g)
    # The delta is EXACTLY the extra drops (conservation).
    assert dr_f - dr_g == dd_g - dd_f

    # Cause-level characterization via the metrics plane: the fast path
    # attributes MORE loss to compaction and no more to faults (a
    # message cut in a slot the generic path never compacts away).
    sf = metrics_mod.snapshot(f1.metrics)
    sg = metrics_mod.snapshot(g1.metrics)
    comp_f = int(sf["drops"][-1, metrics_mod.CAUSE_COMPACT])
    comp_g = int(sg["drops"][-1, metrics_mod.CAUSE_COMPACT])
    fault_f = int(sf["drops"][-1, metrics_mod.CAUSE_FAULT])
    fault_g = int(sg["drops"][-1, metrics_mod.CAUSE_FAULT])
    assert comp_f > comp_g, (comp_f, comp_g)
    assert fault_f <= fault_g, (fault_f, fault_g)
    # Both paths' cause sums reconcile with their legacy counters.
    assert int(sf["drops"][-1].sum()) == dr_f
    assert int(sg["drops"][-1].sum()) == dr_g


def test_group_labels_out_of_range_raises():
    """ADVICE r5 #2: pack_wire_info packs partition group labels into 29
    unsigned bits; labels outside [0, 2^29) would silently alias groups
    and break the fast path's bit-parity with edge_cut.  The host
    boundaries must fail loudly instead."""
    import pytest

    f = faults_mod.none(8, "groups")

    # In-range labels pack fine (eager call, concrete arrays).
    faults_mod.pack_wire_info(f, None)
    ok = f._replace(partition=f.partition.at[3].set(
        faults_mod.GROUP_LABEL_MAX))
    faults_mod.pack_wire_info(ok, None)

    # One bit past the packed field: eager pack_wire_info raises.
    bad = f._replace(partition=f.partition.at[3].set(
        faults_mod.GROUP_LABEL_MAX + 1))
    with pytest.raises(ValueError, match="29 unsigned bits"):
        faults_mod.pack_wire_info(bad, None)

    # Negative labels alias too (sign bits bleed into the shift).
    neg = f._replace(partition=f.partition.at[0].set(-1))
    with pytest.raises(ValueError, match="29 unsigned bits"):
        faults_mod.pack_wire_info(neg, None)

    # The check is advisory inside jit (labels were validated at the
    # host boundary): tracing must not crash on abstract values.
    import jax

    jax.jit(lambda ff: faults_mod.pack_wire_info(ff, None))(ok)

    # inject_partition's groups path re-densifies and validates.
    f2 = faults_mod.inject_partition(f, list(range(4)), list(range(4, 8)))
    assert int(f2.partition.max()) <= faults_mod.GROUP_LABEL_MAX


# ---------------------------------------------------------------------------
# Plane-major <-> legacy-interleaved bit-parity (ISSUE 6): the narrow-
# packed struct-of-planes pipeline must be indistinguishable from the
# int32 interleaved layout in everything observable — state, send-path
# trace, coverage — under the full fault mix.  Base wire width here;
# tests/test_latency.py / test_provenance.py extend the matrix over the
# trailing-word combos.
# ---------------------------------------------------------------------------

def _parity_cfg(pm, **kw):
    from partisan_tpu.config import HyParViewConfig, PlumtreeConfig

    kw.setdefault("partition_mode", "groups")
    kw.setdefault("inbox_cap", 8)
    return Config(n_nodes=64, seed=5, peer_service_manager="hyparview",
                  msg_words=16, max_broadcasts=4,
                  plane_major=pm, hyparview=HyParViewConfig(),
                  plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4),
                  **kw)


def test_plane_parity_base_wire_fast_path():
    """wire_words == msg_words, fast wire path (the bench hot path):
    crashes + groups partition + link drop."""
    from support import plane_parity_case

    plane_parity_case(_parity_cfg, label="base_fast")


def test_plane_parity_base_wire_generic_path():
    """The generic wire path (interposition chain forces it) with
    monotonic-shed backpressure traffic: queued-copy planes (delay
    buffer) and the shed/fault composition stay bit-identical."""
    from support import plane_parity_case

    def mk(pm):
        return _parity_cfg(pm, monotonic_shed=True, inbox_cap=4,
                           egress_delay_ms=1_000)

    plane_parity_case(mk, label="base_generic")


def test_directed_cut_characterization():
    """inject_directed_cut severs exactly the src->dst direction
    (dense mode): forward messages die on the wire, the reverse
    direction and unrelated edges flow, resolve_partition heals, and
    groups mode raises loudly (a single packed per-node label cannot
    express a direction — the fast-wire parity contract stays
    untouched because the fast path requires groups mode)."""
    import numpy as np
    import pytest

    f = faults_mod.none(8, "dense")
    f = faults_mod.inject_directed_cut(f, [1, 2], [5, 6])
    src = jnp.asarray([1, 2, 5, 6, 1, 3])
    dst = jnp.asarray([5, 6, 1, 2, 3, 5])
    cut = faults_mod.edge_cut(f, src, dst, seed=0, rnd=jnp.int32(4),
                              salt=9)
    #       1->5  2->6  5->1  6->2  1->3  3->5
    assert np.asarray(cut).tolist() == [True, True, False, False,
                                        False, False]
    # filter_msgs drops exactly the forward direction
    import partisan_tpu.types as T
    from partisan_tpu.ops import msg as msg_ops

    em = msg_ops.build(12, T.MsgKind.APP,
                       jnp.asarray([[1], [5]]), jnp.asarray([[5], [1]]))
    out = faults_mod.filter_msgs(f, em, seed=0, rnd=jnp.int32(4),
                                 salt=9)
    assert int(out[0, 0, T.W_KIND]) == 0       # 1->5 cut
    assert int(out[1, 0, T.W_KIND]) != 0       # 5->1 flows
    # heal clears the directed cut with everything else
    healed = faults_mod.resolve_partition(f)
    assert not bool(np.asarray(healed.partition).any())
    with pytest.raises(ValueError, match="dense"):
        faults_mod.inject_directed_cut(faults_mod.none(8, "groups"),
                                       [1], [2])
