"""Shared test fixtures — the multi-node-without-a-cluster fixture
analogue (reference test/partisan_support.erl:46+): config factories,
staggered bootstrap, and host-side overlay graph checks."""

import collections
import os

from partisan_tpu.config import Config

# ---------------------------------------------------------------------------
# Tier-1 runtime scale knobs (ISSUE 10 satellite).  The 1-CPU container
# measures the full suite well past the 870 s budget with ZERO failures
# (PR 8 note: five runs timed out at 83-87%; a full baseline run here
# measured 1409 s) — the wall is environmental, and the heaviest tests
# are parameterized by node width / trial count, not by what they
# assert.  These constants shrink those dimensions WITHOUT touching any
# assertion: every oracle gate still runs, over fewer or smaller
# randomized instances.  PARTISAN_TEST_FULL=1 restores the original
# (TPU-sized) parameters for full-fidelity runs.
# ---------------------------------------------------------------------------

FULL = bool(int(os.environ.get("PARTISAN_TEST_FULL", "0") or "0"))
# widest sharded-parity width (tests/test_sharded.py wide-convergence
# parity: 4096 = 512 nodes/shard on mesh8; 768 = 96/shard is the
# floor — it still exercises the a2a quota + multi-wave bootstrap
# cross-shard WITHOUT quota sheds (512 = 64/shard sheds, and a shed
# legitimately diverges the sharded run from the single-device one,
# so the bit-parity assert fails by design there)
WIDE_N = 4096 if FULL else 768
# larger-scale SCAMP conformance band (tests/test_scenarios.py): the
# band is asserted at EVERY scale; 192 is still 1.5x the smoke n
SCAMP_BAND_N = 512 if FULL else 192
# randomized-overlay trials per oracle gate (health BFS / provenance
# trace-replay): the gates assert EXACT parity per overlay either way
# (5 still sweeps faulted/partitioned/churned variants — ISSUE 18
# paydown offsetting the new superstep/pipelined-dispatch suites,
# after ISSUE 15's 16->12->10, ISSUE 16's 10->8 and ISSUE 17's 8->6)
ORACLE_TRIALS = 40 if FULL else 5
# mixed-fault soak width (tests/test_soak.py 500-round storm): the
# storm schedule and every invariant are width-independent (80 keeps
# the crash batches > a quarter of the overlay — ISSUE 14 paydown)
SOAK_N = 256 if FULL else 80
# crash/recover cycles in the p2p-stream soak (tests/test_soak.py):
# every cycle runs the identical guarantee check; 3 still rotates the
# crash through every receiver once
SOAK_CYCLES = 4 if FULL else 3
# node width of the tools-CLI cost-census smoke (tests/test_tools_cli):
# the census is shape-static — the budget verdict is judged at the
# matrix's n=32 regardless, so the smoke width only prices the trace
COST_SMOKE_N = 256 if FULL else 64
# segment-local-FastSV parity sweep (tests/test_sharded_health.py):
# random overlays compared sharded vs gathered vs the BFS oracle.  The
# ISSUE 13 acceptance floor is 50; all trials share TWO compiled
# shard_map programs (fixed padded shape), so extra trials cost only
# host BFS time
FASTSV_TRIALS = 64 if FULL else 50
# fleet-runner suite (tests/test_fleet.py) scale knobs: the parity /
# storm assertions are width- and size-independent (every member is
# compared bit-for-bit against its own serial run), so tier-1 shrinks
# the populations without touching an assertion.  FLEET_SEARCH_W stays
# at the ISSUE 14 acceptance floor (a W>=64 search must be ONE jitted
# program) in both modes — the members are 16-node clusters, so width
# is cheap; it is the serial comparisons that scale with width.
FLEET_PAR_W = 8 if FULL else 4          # fleet-vs-loop parity width
FLEET_SEARCH_W = 64                     # acceptance floor, both modes
FLEET_TUNE_N = 128 if FULL else 64      # tune harness overlay size
FLEET_TUNE_WAVES = 12 if FULL else 3    # broadcast waves per tune run
#   (3: tune only ranks candidate bands — every wave re-runs the same
#   jitted member program, so fewer waves trims wall without touching
#   an assertion — ISSUE 16 paydown 12->6, ISSUE 17 6->5, ISSUE 18
#   5->4, ISSUE 19 4->3 offsetting the spool suites; 3 still ranks the
#   adaptive band ahead of static at full coverage, deterministically)
# incident-observatory soak width (tests/test_incident.py): the span
# matcher and kill/restore parity are width-independent — 24 keeps the
# 5% crash batch >= one node and the partition two real components
# (ISSUE 19 paydown 32->24, offsetting the new spool suites)
OPS_SOAK_N = 48 if FULL else 24


def hv_config(n, seed, **kw):
    kw.setdefault("msg_words", 16)
    return Config(n_nodes=n, seed=seed, peer_service_manager="hyparview",
                  **kw)


def fm_config(n, seed, **kw):
    kw.setdefault("inbox_cap", max(32, n + 8))
    return Config(n_nodes=n, seed=seed, **kw)


def boot_fullmesh(cl, contact=0, settle=15):
    """All nodes join via the contact, then membership gossip settles."""
    st = cl.init()
    m = st.manager
    for i in range(cl.cfg.n_nodes):
        if i != contact:
            m = cl.manager.join(cl.cfg, m, i, contact)
    st = st._replace(manager=m)
    return cl.steps(st, settle)


def staggered_join(cl, st, contact=0):
    """Each node joins via the contact, a few per round (the reference
    suite boots nodes one at a time, partisan_support.erl:46+)."""
    cfg = cl.cfg
    for base in range(1, cfg.n_nodes, 4):
        m = st.manager
        for i in range(base, min(base + 4, cfg.n_nodes)):
            m = cl.manager.join(cfg, m, i, contact)
        st = st._replace(manager=m)
        st = cl.steps(st, 2)
    return st


def boot_hyparview(cl, settle=40):
    return cl.steps(staggered_join(cl, cl.init()), settle)


def normalize_wire(tree):
    """Map every plane-major record buffer (ops/plane.Planes pytree
    node) in a state tree to its interleaved int32 wire tensor, leaving
    everything else untouched — the layout normalizer the plane-vs-
    legacy bit-parity tests compare through (word VALUES are the
    contract; the storage layout is not)."""
    import jax

    from partisan_tpu.ops import plane as plane_ops

    return jax.tree.map(
        lambda x: plane_ops.interleave(x) if plane_ops.is_planes(x)
        else x,
        tree, is_leaf=plane_ops.is_planes)


def assert_states_bitidentical(a, b, label=""):
    """Every leaf of two (layout-normalized) state trees equal
    bit-for-bit."""
    import jax
    import jax.tree_util as jtu
    import numpy as np

    la = jtu.tree_leaves_with_path(normalize_wire(a))
    lb = jtu.tree_leaves_with_path(normalize_wire(b))
    assert len(la) == len(lb), (label, len(la), len(lb))
    for (pa, xa), (_pb, xb) in zip(la, lb):
        xa = np.asarray(jax.device_get(xa))
        xb = np.asarray(jax.device_get(xb))
        where = label + jtu.keystr(pa)
        assert xa.shape == xb.shape, (where, xa.shape, xb.shape)
        assert np.array_equal(xa, xb), \
            f"{where}: {np.sum(xa != xb)} of {xa.size} elements differ"


def plane_parity_case(mk_cfg, *, drive=None, record_k=8, label=""):
    """The plane-major <-> legacy-interleaved bit-parity harness: build
    two clusters differing ONLY in ``Config.plane_major``, drive the
    same scenario, and assert state (layout-normalized), send-path
    trace, coverage and convergence are bit-identical.  ``mk_cfg(pm)``
    returns the Config for one layout; ``drive(cl)`` runs the scenario
    and returns the final state (default: hyparview bootstrap +
    plumtree broadcast + crash/partition/link-drop mix)."""
    import jax.numpy as jnp
    import numpy as np

    from partisan_tpu.cluster import Cluster
    from partisan_tpu.models.plumtree import Plumtree

    def default_drive(cl):
        # ONE scan length throughout (k=10): each phase change would
        # otherwise compile its own full-width scan per layout — the
        # tier-1 suite's six parity harnesses paid 3 programs × 2
        # layouts each for no extra coverage (the assertion is layout
        # bit-parity, not phase granularity).
        n = cl.cfg.n_nodes
        st = cl.init()
        m = cl.manager.join_many(
            cl.cfg, st.manager, list(range(1, n)), [0] * (n - 1))
        st = cl.steps(st._replace(manager=m), 10)
        st = cl.steps(st, 10)
        st = st._replace(model=cl.model.broadcast(st.model, 0, 0, 7))
        alive = st.faults.alive.at[jnp.asarray([3, 11])].set(False)
        part = st.faults.partition.at[jnp.arange(n // 2)].set(1)
        st = st._replace(faults=st.faults._replace(
            alive=alive, partition=part, link_drop=jnp.float32(0.1)))
        st = cl.steps(st, 10)
        st = st._replace(faults=st.faults._replace(
            partition=jnp.zeros_like(part), link_drop=jnp.float32(0.0)))
        return cl.steps(st, 10)

    drive = drive or default_drive
    outs = {}
    for pm in (True, False):
        cl = Cluster(mk_cfg(pm), model=Plumtree())
        st = drive(cl)
        st2, tr = cl.record(st, record_k)
        cov = float(cl.model.coverage(st2.model, st2.faults.alive, 0))
        outs[pm] = (st2, tr, cov)
    st_p, tr_p, cov_p = outs[True]
    st_l, tr_l, cov_l = outs[False]
    assert_states_bitidentical(st_p, st_l, label or "plane_vs_legacy")
    assert np.array_equal(np.asarray(tr_p.rnd), np.asarray(tr_l.rnd))
    assert np.array_equal(np.asarray(tr_p.sent), np.asarray(tr_l.sent)), \
        "send-path traces diverge between wire layouts"
    assert np.array_equal(np.asarray(tr_p.dropped),
                          np.asarray(tr_l.dropped))
    assert cov_p == cov_l
    return st_p, st_l


# ---------------------------------------------------------------------------
# Shared jaxpr-lint wrappers (partisan_tpu/lint): the single home of the
# per-plane "no host callback inside the scan" and "zero cost when off"
# checks that used to be copy-pasted string greps in
# test_{metrics,health,latency,provenance}.py.  The lint rules are
# strictly stronger: the callback check walks every sub-jaxpr's
# primitive names (not str(jaxpr) substrings), and the zero-cost check
# reads each equation's named_scope stack — which ``str(jaxpr)`` never
# contains, so the old ``"round.latency" not in jaxpr`` asserts were
# vacuous.
# ---------------------------------------------------------------------------

SCAN_LINT_RULES = ("no-host-callback", "zero-cost-when-off",
                   "narrow-dtype-overflow", "scatter-overlap")


def lint_scan(cl, st, k=8, *, rules=SCAN_LINT_RULES, name="test-scan"):
    """Trace ``cl``'s k-round scan program and run the shared lint
    rules over it (waiver baseline applied).  The interleave-budget
    rule is excluded by default: its width window {msg_words..
    wire_words} must be disjoint from other trailing dims, which only
    configs built for it (msg_words=17) guarantee."""
    from partisan_tpu import lint

    prog = lint.trace_program(name, lambda s: cl._scan(s, k), st,
                              cl.cfg)
    return lint.run_programs([prog], rules=list(rules),
                             package_rules=[])


def assert_scan_lint_clean(cl, st, k=8, **kw):
    """The migrated per-plane scan assert: zero unwaived lint findings
    on the jitted k-round program."""
    rep = lint_scan(cl, st, k, **kw)
    assert not rep.findings, \
        [f"{f.fingerprint}: {f.message}" for f in rep.findings]
    return rep


def components(active, alive, partition=None):
    """Connected components of the overlay (undirected union of active
    views), host-side — the numpy BFS the device health plane's
    pointer-jumping counter (partisan_tpu/health.py) is gated against.
    ``partition`` optionally severs edges the way faults.py does:
    a 1-D groups vector cuts edges between differing labels, a 2-D
    dense matrix cuts where True."""
    n = active.shape[0]

    def cut(i, j):
        if partition is None:
            return False
        p = partition
        return bool(p[i, j]) if getattr(p, "ndim", 1) == 2 \
            else p[i] != p[j]

    adj = collections.defaultdict(set)
    for i in range(n):
        if not alive[i]:
            continue
        for j in active[i]:
            j = int(j)
            if j >= 0 and alive[j] and not cut(i, j):
                adj[i].add(j)
                adj[j].add(i)
    seen, comps = set(), []
    for s in range(n):
        if not alive[s] or s in seen:
            continue
        comp, stack = set(), [s]
        while stack:
            x = stack.pop()
            if x in comp:
                continue
            comp.add(x)
            stack.extend(adj[x] - comp)
        seen |= comp
        comps.append(comp)
    return comps


# ---------------------------------------------------------------------------
# Provenance-plane trace-replay oracle (tests/test_provenance.py): replay
# a captured send-path trace into parent/hop/duplicate tables, host-side
# and loop-based — the independent implementation the device accumulator
# (partisan_tpu/provenance.py record_round) is gated against.
# ---------------------------------------------------------------------------

class ProvenanceOracle:
    """Replays ``Cluster.record`` captures ((sent, dropped) per round)
    through the generic wire path's delivery semantics — post-fault
    stack, optional emission compaction, route()'s src-major stable
    order with inbox_cap truncation, dead-receiver masking — and
    accumulates the provenance tables with plain Python loops.

    Constraints the caller's Config must satisfy for ctl EMITTED parity
    (the captured ``sent`` must equal the accumulator's pre-wire stack):
    no interposition chain, no channel-capacity stage, and
    ``monotonic_shed=False`` — the wire stages between the two
    reference points must be kind-preserving.  The forest/redundancy
    tables have no such constraint: both sides read the delivered set.

    ``alive`` is per replay() call — the fault mask is host-set between
    recorded batches and constant within one (round_body never writes
    ``state.faults``)."""

    def __init__(self, cfg, spec):
        import numpy as np

        from partisan_tpu import provenance as prov_mod

        self.cfg, self.spec = cfg, spec
        n, B, C = cfg.n_nodes, cfg.max_broadcasts, cfg.n_channels
        self.parent = np.full((n, B), -1, np.int64)
        self.hop = np.zeros((n, B), np.int64)
        self.claim_rnd = np.full((n, B), -1, np.int64)
        self.epoch = np.zeros((n, B), np.int64)
        self.depth_hwm = np.zeros(B, np.int64)
        self.cover_rnd = np.full(B, -1, np.int64)
        self.rows = {}        # rnd -> {dup[C], gossip, claims, ctl}
        self.dup_total = 0
        self.gossip_total = 0
        self.n_ch = C
        self.bits = max(1, (n - 1).bit_length())
        self.hop_max = (1 << (30 - self.bits)) - 1
        self.ctl_kinds = prov_mod.CTL_KINDS

    def mark_origin(self, node, slot, rnd=0, epoch=None):
        self.parent[node, slot] = node
        self.hop[node, slot] = 0
        self.claim_rnd[node, slot] = rnd
        if epoch is not None:
            self.epoch[node, slot] = max(self.epoch[node, slot], epoch)

    def replay(self, sent, dropped, rounds, alive):
        """Replay one recorded batch: sent int32[T, n, E, W], dropped
        bool[T, n, E], rounds int[T], alive bool[n] (constant over the
        batch)."""
        import numpy as np

        sent = np.asarray(sent)
        dropped = np.asarray(dropped)
        alive = np.asarray(alive)
        for t in range(sent.shape[0]):
            self._one_round(sent[t], dropped[t], int(rounds[t]), alive)

    def _one_round(self, sent, dropped, rnd, alive):
        import numpy as np

        from partisan_tpu import types as T

        cfg, spec = self.cfg, self.spec
        n, E, _W = sent.shape
        B = cfg.max_broadcasts
        ps_w, ph_w = cfg.msg_words, cfg.msg_words + 1

        # ctl EMITTED: every live slot of the pre-fault stack
        kind_all = sent[..., T.W_KIND]
        ctl_e = [int((kind_all == k).sum()) for k in self.ctl_kinds]

        # post-fault stack -> optional compaction -> route (src-major
        # stable order, first inbox_cap per destination)
        kind = np.where(dropped, 0, kind_all)
        live = kind != 0
        if cfg.emit_compact and cfg.emit_compact < E:
            rank = np.cumsum(live, axis=1) - 1
            live = live & (rank < cfg.emit_compact)
        inbox = [[] for _ in range(n)]
        for s, e in zip(*np.nonzero(live)):
            d = int(sent[s, e, T.W_DST])
            if 0 <= d < n and len(inbox[d]) < cfg.inbox_cap:
                inbox[d].append(sent[s, e])

        # delivered set: routed AND receiver alive (the pre-dead-mask
        # inbox with the dead rows excluded — provenance.record_round's
        # `delivered`)
        ctl_d = [0] * len(self.ctl_kinds)
        copies = []      # (i, b, epoch, hop, src, pos, channel)
        for i in range(n):
            if not alive[i]:
                continue
            for pos, m in enumerate(inbox[i]):
                k = int(m[T.W_KIND])
                for j, ck in enumerate(self.ctl_kinds):
                    if k == ck:
                        ctl_d[j] += 1
                if spec is None or k != spec.kind:
                    continue
                if spec.match_word is not None and \
                        int(m[spec.match_word]) != spec.match_val:
                    continue
                b = min(max(int(m[spec.slot_word]), 0), B - 1)
                ep = (int(m[spec.epoch_word])
                      if spec.epoch_word is not None else 0)
                hp = min(max(int(m[ph_w]), 0), self.hop_max)
                src = min(max(int(m[ps_w]), 0), cfg.n_nodes - 1)
                ch = min(max(int(m[T.W_CHANNEL]), 0), self.n_ch - 1)
                copies.append((i, b, ep, hp, src, pos, ch))

        # slot-epoch guard: a higher delivered epoch resets the entry;
        # stale-epoch copies stay in the duplicate count
        if spec is not None and spec.epoch_word is not None:
            ep_new = self.epoch.copy()
            for (i, b, ep, _hp, _src, _pos, _ch) in copies:
                ep_new[i, b] = max(ep_new[i, b], ep)
            bumped = ep_new > self.epoch
            self.parent[bumped] = -1
            self.hop[bumped] = 0
            self.claim_rnd[bumped] = -1
            self.epoch = ep_new
            cur = [c for c in copies if c[2] == self.epoch[c[0], c[1]]]
        else:
            cur = copies

        # first-delivery claims: min (hop, src) key, min inbox slot
        # among key-minimal copies is THE claim copy
        best = {}
        for (i, b, _ep, hp, src, pos, ch) in cur:
            if self.parent[i, b] >= 0:
                continue
            cand = ((hp, src), pos)
            if (i, b) not in best or cand < best[(i, b)]:
                best[(i, b)] = cand
        for (i, b), ((hp, src), _pos) in best.items():
            self.parent[i, b] = src
            self.hop[i, b] = hp + 1
            self.claim_rnd[i, b] = rnd
        claim_pos = {(i, b): pos for (i, b), (_k, pos) in best.items()}
        dup_ch = np.zeros(self.n_ch, np.int64)
        for (i, b, _ep, _hp, _src, pos, ch) in copies:
            if claim_pos.get((i, b)) != pos:
                dup_ch[ch] += 1

        # depth high-water mark + time-to-coverage
        claimed = self.parent >= 0
        self.depth_hwm = np.maximum(
            self.depth_hwm, np.where(claimed, self.hop, 0).max(axis=0))
        n_alive = int(alive.sum())
        cnt = (claimed & alive[:, None]).sum(axis=0)
        full = (n_alive > 0) & (cnt == n_alive)
        newly = (self.cover_rnd < 0) & full
        self.cover_rnd[newly] = rnd

        self.rows[rnd] = {
            "dup": dup_ch, "gossip": len(copies), "claims": len(best),
            "ctl": np.stack([np.asarray(ctl_e), np.asarray(ctl_d)],
                            axis=-1),
        }
        self.dup_total += int(dup_ch.sum())
        self.gossip_total += len(copies)


# ---------------------------------------------------------------------------
# Bridge-transport VM base (shared by the OTP-conformance suites): one
# emulated BEAM node holding a TCP connection to the shared simulator
# (bridge/socket_server.py).  See tests/test_bridge_gen_server.py for the
# first user of this pattern.
# ---------------------------------------------------------------------------

def recv_exact(sock, k):
    """Canonical {packet,4} frame reader (raises on a closed socket) —
    re-exported from the bridge package for the test rigs."""
    from partisan_tpu.bridge.socket_server import recv_exact as rx
    return rx(sock, k)


def bridge_rig(n_nodes, seed=9):
    """Start a BridgeSocketServer and init the shared simulator.  Returns
    the server; callers attach BridgeVM instances and must close both."""
    import socket
    import struct

    from partisan_tpu.bridge import etf
    from partisan_tpu.bridge.etf import Atom
    from partisan_tpu.bridge.socket_server import BridgeSocketServer

    srv = BridgeSocketServer()
    srv.serve_background()
    boot = socket.create_connection((srv.host, srv.port))
    payload = etf.encode((Atom("init"), {Atom("n_nodes"): n_nodes,
                                         Atom("seed"): seed}))
    boot.sendall(struct.pack(">I", len(payload)) + payload)
    recv_exact(boot, struct.unpack(">I", recv_exact(boot, 4))[0])
    boot.close()
    return srv


class BridgeVM:
    """One emulated BEAM node on the shared simulator."""

    def __init__(self, srv, sim_id):
        import socket

        from partisan_tpu.bridge import etf
        from partisan_tpu.bridge.etf import Atom

        self._etf = etf
        self._Atom = Atom
        self.id = sim_id
        self.sock = socket.create_connection((srv.host, srv.port))
        assert self.rpc((Atom("set_self"), sim_id)) == etf.OK

    def rpc(self, term):
        import struct

        payload = self._etf.encode(term)
        self.sock.sendall(struct.pack(">I", len(payload)) + payload)
        (n,) = struct.unpack(">I", recv_exact(self.sock, 4))
        return self._etf.decode(recv_exact(self.sock, n))

    def forward(self, dst, words):
        assert self.rpc((self._Atom("forward_message"), self.id, dst,
                         list(words))) == self._etf.OK

    def drain(self):
        ok, out = self.rpc((self._Atom("drain"),))
        assert ok == self._etf.OK
        return out

    def step(self, k=1):
        ok, rnd = self.rpc((self._Atom("step"), k))
        assert ok == self._etf.OK
        return rnd

    def is_alive(self, node):
        ok, alive = self.rpc((self._Atom("is_alive"), node))
        assert ok == self._etf.OK
        return bool(alive)

    def close(self):
        self.sock.close()
