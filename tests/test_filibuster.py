"""Filibuster model-checker tests (reference test/filibuster_SUITE.erl):
the checker finds a single-omission counterexample against unacked direct
mail (no retransmission => reliable broadcast fails), and certifies the
acked variant against the same fault budget (retransmission repairs every
single omission)."""

from partisan_tpu import filibuster
from partisan_tpu.cluster import Cluster
from partisan_tpu.models.direct_mail import DirectMail
from tests.support import fm_config, boot_fullmesh

N = 6
HORIZON = 12


def _build_fn(acked):
    model = DirectMail(acked=acked)

    def build(interp):
        cfg = fm_config(N, seed=17, ack_cap=8 if acked else 0)
        cl = Cluster(cfg, model=model, interpose=interp)
        st = boot_fullmesh(cl)
        st = st._replace(model=model.broadcast(st.model, 0, 0))
        return cl, st

    return model, build


def _assertion(model):
    # Reliable broadcast: every (alive) node eventually delivers.
    def check(cl, st):
        return float(model.coverage(st.model, st.faults.alive, 0)) == 1.0
    return check


def test_finds_counterexample_for_unacked_direct_mail():
    model, build = _build_fn(acked=False)
    checker = filibuster.Checker(
        build=build, horizon=HORIZON, assertion=_assertion(model),
        candidate=filibuster.app_messages, max_faults=1)
    res = checker.run()
    assert not res.passed
    assert len(res.counterexample.schedule) == 1  # shrunk to minimal
    assert "omit" in res.render() and "APP" in res.render()


def test_certifies_acked_direct_mail_single_omission():
    model, build = _build_fn(acked=True)
    checker = filibuster.Checker(
        build=build, horizon=HORIZON, assertion=_assertion(model),
        candidate=filibuster.app_messages, max_faults=1)
    res = checker.run()
    assert res.passed, res.render()
    assert res.executions >= N  # base + one per first-mailing candidate
    assert "PASSED" in res.render()


def test_budget_two_prunes_and_bounds():
    model, build = _build_fn(acked=False)
    checker = filibuster.Checker(
        build=build, horizon=HORIZON, assertion=_assertion(model),
        candidate=filibuster.app_messages, max_faults=2,
        max_executions=30)
    res = checker.run()
    # Still fails at depth 1 — deeper budget must not hide the minimal cex.
    assert not res.passed
    assert len(res.counterexample.schedule) == 1


def test_iter_schedules_enumeration():
    cands = [(0, 1, 0), (0, 2, 0), (1, 1, 1)]
    scheds = list(filibuster.iter_schedules(cands, 2))
    assert frozenset({(0, 1, 0)}) in scheds
    assert frozenset({(0, 1, 0), (1, 1, 1)}) in scheds
    assert all(len(s) <= 2 for s in scheds)
    assert len(scheds) == 3 + 3


def test_annotation_pruning_reduces_candidates():
    """Causality annotations prune omission candidates that cannot affect
    the target kind (the partisan_analysis -> schedule_valid_causality
    pipeline)."""
    from partisan_tpu import analysis

    model, build = _build_fn(acked=True)
    # Record a golden run to derive the reaction graph.
    cl, st = build(None)
    _, cap = cl.record(st, HORIZON)
    from partisan_tpu import trace as trace_mod
    tr = trace_mod.from_capture(cap)
    g = analysis.reaction_graph(tr)

    # Ack-retransmission implication: losing an ACK re-triggers APP
    # retransmission, so ACK must NOT be prunable against target APP
    # (the unsound-pruning regression).
    assert "APP" in g.get("ACK", set())

    def any_kind(ev):
        return ev.kind_name in ("APP", "ACK", "PING", "PONG")

    pruned = filibuster.Checker(
        build=build, horizon=HORIZON, assertion=_assertion(model),
        candidate=any_kind, max_faults=1, max_executions=5,
        reaction=g, target_kinds=("APP",))
    base_p = pruned._execute(frozenset())
    cp = pruned._candidates(base_p.trace)
    kinds_kept = {e.kind_name for e in base_p.trace.events()
                  if (e.rnd, e.src, e.slot) in set(cp)}
    assert "APP" in kinds_kept and "ACK" in kinds_kept
    # Pruning logic itself: a kind with no path to the target is skipped.
    pruned.reaction = {"PONG": set(), **g}
    pruned._closure = None
    assert not pruned._relevant_kind("PONG")
    assert pruned._relevant_kind("ACK") and pruned._relevant_kind("APP")


def test_unsound_pruning_demo_default_still_finds_absence_bug():
    """The soundness boundary of trace-derived pruning, demonstrated: a
    protocol with an ABSENCE-triggered reaction (a watchdog that alarms
    when expected data never arrives) has no APP -> RPC_CALL receipt
    edge in ANY trace — so opt-in pruning against that graph wrongly
    skips the one schedule that fires the alarm, while the DEFAULT
    (reaction=None, exhaustive within budget) executes it and finds the
    counterexample.  This is why pruning is opt-in (the reference's
    static source analysis over-approximates and does not have this
    hole, partisan_analysis.erl:24-60)."""
    import jax.numpy as jnp
    from typing import NamedTuple

    from partisan_tpu import analysis, trace as trace_mod
    from partisan_tpu import types as T
    from partisan_tpu.ops import msg as msg_ops

    SEND_R, DEADLINE = 2, 6

    class WDState(NamedTuple):
        got: jnp.ndarray         # bool[n] — node received the data
        alarm_seen: jnp.ndarray  # bool[n] — node received an alarm

    class Watchdog:
        name = "watchdog"

        def init(self, cfg, comm):
            n = comm.n_local
            return WDState(got=jnp.zeros((n,), jnp.bool_),
                           alarm_seen=jnp.zeros((n,), jnp.bool_))

        def step(self, cfg, comm, state, ctx, nbrs):
            gids = comm.local_ids()
            inb = ctx.inbox.data
            kinds = inb[..., T.W_KIND]
            got = state.got | (kinds == T.MsgKind.APP).any(axis=1)
            alarm_seen = state.alarm_seen | \
                (kinds == T.MsgKind.RPC_CALL).any(axis=1)
            send_data = (ctx.rnd == SEND_R) & (gids == 0)
            alarm = (ctx.rnd == DEADLINE) & (gids == 1) & ~got
            emitted = jnp.concatenate([
                msg_ops.build(cfg.msg_words, T.MsgKind.APP, gids,
                              jnp.where(send_data, 1, -1))[:, None],
                msg_ops.build(cfg.msg_words, T.MsgKind.RPC_CALL, gids,
                              jnp.where(alarm, 0, -1))[:, None],
            ], axis=1)
            return WDState(got=got, alarm_seen=alarm_seen), emitted

    model = Watchdog()

    def build(interp):
        cfg = fm_config(4, seed=3)
        cl = Cluster(cfg, model=model, interpose=interp)
        return cl, cl.init()

    def assertion(cl, st):
        return not bool(st.model.alarm_seen.any())

    def cand(ev):
        return ev.kind_name == "APP"

    # The golden trace has no APP -> RPC_CALL edge (the alarm never
    # fired), so pruning against it considers APP-omissions irrelevant
    # to the RPC_CALL target and MISSES the bug...
    cl, st = build(None)
    _, cap = cl.record(st, 10)
    g = analysis.reaction_graph(trace_mod.from_capture(cap))
    assert "RPC_CALL" not in g.get("APP", set())
    pruned = filibuster.Checker(
        build=build, horizon=10, assertion=assertion, candidate=cand,
        max_faults=1, reaction=g, target_kinds=("RPC_CALL",))
    res_pruned = pruned.run()
    assert res_pruned.passed, "pruning unexpectedly kept the schedule"

    # ...while the DEFAULT (no pruning) executes it and fails loudly.
    default = filibuster.Checker(
        build=build, horizon=10, assertion=assertion, candidate=cand,
        max_faults=1)
    res = default.run()
    assert not res.passed
    assert len(res.counterexample.schedule) == 1
    assert "APP" in res.render()

    # Even an ensemble over BOTH traces can't see the absence edge —
    # the structural reason pruning stays opt-in — but the coverage
    # report makes the evidence base explicit.
    g2, cov = analysis.ensemble_reaction(
        [res.base_trace, res.counterexample.trace])
    assert "RPC_CALL" not in g2.get("APP", set())
    assert cov["traces"] == 2 and "RPC_CALL" in cov["background"]
