"""Routing kernel tests: ordering, overflow accounting, sharnel fan-in."""

import jax.numpy as jnp

from partisan_tpu.ops import exchange
from partisan_tpu.ops import msg as msg_ops
from partisan_tpu import types as T

W = 12


def build(src, dst, kind=T.MsgKind.APP, **kw):
    return msg_ops.build(W, kind, jnp.int32(src), jnp.int32(dst), **kw)


def test_route_basic():
    # 3 nodes; node 0 sends 2 msgs to node 2, node 1 sends 1 msg to node 0.
    emitted = jnp.stack([
        jnp.stack([build(0, 2, payload=(jnp.int32(10),)),
                   build(0, 2, payload=(jnp.int32(11),))]),
        jnp.stack([build(1, 0, payload=(jnp.int32(12),)), jnp.zeros((W,), jnp.int32)]),
        jnp.zeros((2, W), jnp.int32),
    ])
    inbox = exchange.route(emitted, n=3, cap=4)
    assert inbox.count.tolist() == [1, 0, 2]
    assert inbox.drops.tolist() == [0, 0, 0]
    assert int(inbox.data[0, 0, T.P0]) == 12
    # Sender order preserved (stable sort):
    assert int(inbox.data[2, 0, T.P0]) == 10
    assert int(inbox.data[2, 1, T.P0]) == 11
    # Empty slots stay NONE:
    assert int(inbox.data[0, 1, T.W_KIND]) == 0


def test_route_overflow_drops():
    # 8 senders all target node 0 with cap 4 -> 4 delivered, 4 dropped.
    emitted = jnp.stack([build(i, 0)[None] for i in range(8)])
    inbox = exchange.route(emitted, n=8, cap=4)
    assert int(inbox.count[0]) == 4
    assert int(inbox.drops[0]) == 4
    assert int(jnp.sum(inbox.count)) == 4


def test_route_invalid_dst_ignored():
    emitted = jnp.stack([build(0, -1)[None], build(1, 99)[None]])
    inbox = exchange.route(emitted, n=2, cap=4)
    assert int(jnp.sum(inbox.count)) == 0


def test_route_node_offset():
    # Shard owning global nodes [4, 8): only dst in range land.
    emitted = jnp.stack([build(0, 5)[None], build(1, 2)[None]])
    inbox = exchange.route(emitted, n=4, cap=4, node_offset=4)
    assert inbox.count.tolist() == [0, 1, 0, 0]


def test_merge_inboxes():
    a = exchange.route(build(0, 1)[None, None], n=2, cap=4)
    b = exchange.route(build(1, 1, payload=(jnp.int32(7),))[None, None].at[:, :, T.W_SRC].set(1), n=2, cap=4)
    m = exchange.merge_inboxes(a, b)
    assert int(m.count[1]) == 2
    assert int(m.data[1, 0, T.W_SRC]) == 0   # a's message first
    assert int(m.data[1, 1, T.P0]) == 7


def test_per_sender_fifo_ordering():
    """The tensor transport preserves each sender's emission order at
    every receiver (stable sort in route) — STRONGER than the
    reference's per-connection-lane-only FIFO (partisan channels with
    parallelism > 1 may reorder across lanes; partisan_peer_connections
    dispatch :897-925), so `with_partition_key` ordering holds for free."""
    n, e = 4, 6
    # Sender 1 emits a numbered sequence to receiver 0 across different
    # lanes/channels; sender 2 interleaves its own.
    seqs = {1: [10, 11, 12, 13], 2: [20, 21]}
    emitted = jnp.zeros((n, e, W), jnp.int32)
    for s, vals in seqs.items():
        for i, v in enumerate(vals):
            rec = build(s, 0, channel=i % 3, lane=i % 2,
                        payload=(jnp.int32(v),))
            emitted = emitted.at[s, i].set(rec)
    inbox = exchange.route(emitted, n, cap=16)
    got = [(int(r[T.W_SRC]), int(r[T.HDR_WORDS]))
           for r in inbox.data[0] if r[T.W_KIND] != 0]
    per_sender = {s: [v for (src, v) in got if src == s] for s in seqs}
    assert per_sender[1] == seqs[1]
    assert per_sender[2] == seqs[2]
    assert int(inbox.count[0]) == 6
