"""Long-horizon mixed-fault soak: the system-level invariants under a
rolling storm of every fault class the test plane models.

The reference's long-running robustness evidence is its CT suites
cycling crash/partition/churn per group (partisan_SUITE.erl groups,
:214-315) — this is the simulator's equivalent: one 500-round run over
repeating fault cycles (iid link drop → crash batch → full partition →
heal → churn), asserting after EVERY heal window that

- the alive overlay re-converges to ONE component (healing works
  regardless of what the storm broke),
- a fresh plumtree broadcast reaches every alive node (the data plane
  recovers, not just the membership plane),
- stats accounting stays consistent (emitted == delivered + dropped —
  the round engine's conservation law).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from partisan_tpu import checkpoint, faults as faults_mod, soak, telemetry
from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config
from partisan_tpu.models.plumtree import Plumtree

from support import (SOAK_N, assert_states_bitidentical, boot_hyparview,
                     components, hv_config)

N = SOAK_N


def _one_component(st) -> bool:
    alive = np.asarray(st.faults.alive)
    comps = components(np.asarray(st.manager.active), alive)
    return len(comps) == 1


def test_soak_500_rounds_mixed_faults():
    cfg = hv_config(N, seed=23, partition_mode="dense", max_broadcasts=8,
                    inbox_cap=16)
    model = Plumtree()
    cl = Cluster(cfg, model=model)
    st = boot_hyparview(cl)
    window = cfg.rounds(cfg.hyparview.isolation_window_ms)
    rng = np.random.default_rng(41)
    slot = 0

    def heal_and_check(st, slot, phase):
        # clear all faults, give the heartbeat healing one window
        st = st._replace(faults=faults_mod.none(
            N, cfg.resolved_partition_mode)._replace(
                alive=st.faults.alive))
        alive_ids = np.flatnonzero(np.asarray(st.faults.alive))
        st = cl.steps(st, window + 30)
        assert _one_component(st), f"{phase}: overlay did not re-merge"
        src = int(rng.choice(alive_ids))
        ver = int(st.rnd)
        st = st._replace(model=model.broadcast(st.model, src, slot, ver))
        st, r = cl.run_until(
            st, lambda s, _sl=slot, _v=ver: float(model.coverage(
                s.model, s.faults.alive, _sl, version=_v)) >= 1.0,
            max_rounds=150, check_every=10)
        assert r != -1, f"{phase}: broadcast did not re-converge"
        s = st.stats
        assert int(s.emitted) == int(s.delivered) + int(s.dropped), phase
        return st, (slot + 1) % cfg.max_broadcasts

    # phase 1: iid link drop storm
    st = st._replace(faults=st.faults._replace(link_drop=jnp.float32(0.3)))
    st = cl.steps(st, 60)
    st, slot = heal_and_check(st, slot, "after link-drop storm")

    # phase 2: crash a random tenth of the cluster (one scatter)
    victims = rng.choice(N, size=N // 10, replace=False)
    st = st._replace(faults=faults_mod.crash_many(
        st.faults, [int(v) for v in victims]))
    st = cl.steps(st, 60)
    st, slot = heal_and_check(st, slot, "after crash batch")

    # phase 3: full partition (two halves), then heal
    live = np.flatnonzero(np.asarray(st.faults.alive))
    half = live[: len(live) // 2]
    other = live[len(live) // 2:]
    st = st._replace(faults=faults_mod.inject_partition(
        st.faults, [int(x) for x in half], [int(x) for x in other]))
    st = cl.steps(st, 60)
    st, slot = heal_and_check(st, slot, "after partition")

    # phase 4: churn (birth/death) for 100 rounds
    churn = lambda f, rnd: faults_mod.churn_step(  # noqa: E731
        f, cfg.seed, rnd, 0.01, 0.01)
    for _ in range(10):
        st = st._replace(faults=churn(st.faults, st.rnd))
        st = cl.steps(st, 10)
    st, slot = heal_and_check(st, slot, "after churn")

    assert int(st.rnd) >= 500, int(st.rnd)


def test_soak_p2p_streams_under_crash_recovery_cycles():
    """Delivery-plane soak: long-horizon p2p-causal streams while their
    receivers repeatedly crash and recover.  Across every cycle the
    per-edge guarantee must hold: each receiver's log is duplicate-free
    and per-sender FIFO (crash windows may drop in-flight sends — the
    reference's causality backend loses what a dead node never stored —
    but nothing may be reordered or delivered twice)."""
    from partisan_tpu.config import Config
    from partisan_tpu.models.p2p_chat import P2PChat

    n = 32
    cfg = Config(n_nodes=n, seed=31, causal_p2p_labels=("chat",),
                 peer_service_manager="static")
    model = P2PChat()
    cl = Cluster(cfg, model=model)
    st = cl.init()
    rng = np.random.default_rng(17)
    senders = [1, 2, 3]
    receivers = [20, 21, 22]

    from support import SOAK_CYCLES

    for cycle in range(SOAK_CYCLES):
        # each sender fires two messages at its receiver this cycle
        m = st.model
        base = int(st.rnd)
        for i, s in enumerate(senders):
            m = model.schedule(m, node=s, rnd=base + 2, dst=receivers[i],
                               now=base + 1)
            m = model.schedule(m, node=s, rnd=base + 5, dst=receivers[i],
                               now=base + 1)
        st = st._replace(model=m)
        # crash one receiver mid-stream, then recover it
        victim = receivers[cycle % len(receivers)]
        st = cl.steps(st, 3)
        st = st._replace(faults=faults_mod.crash(st.faults, victim))
        st = cl.steps(st, 4)
        st = st._replace(faults=faults_mod.recover(st.faults, victim))
        st = cl.steps(st, cfg.retransmit_every * 6 + 6)

    logs = P2PChat.logs(st.model)
    delivered = 0
    for r in receivers:
        log = logs[r]
        assert len(log) == len(set(log)), f"node {r} duplicates: {log}"
        per_src = {}
        for t in log:
            per_src.setdefault(t // P2PChat.K, []).append(t % P2PChat.K)
        for src, seqs in per_src.items():
            assert seqs == sorted(seqs), \
                f"node {r} reordered stream from {src}: {seqs}"
        delivered += len(log)
    # the never-crashed cycles must deliver fully: at least half of all
    # sends land even with one receiver down per cycle
    total = 6 * SOAK_CYCLES
    assert delivered >= total // 2, \
        f"only {delivered} of {total} sends delivered"


def test_boot_ladder_single_component_aligned_timers():
    """Regression guard for the r5 fragmentation fix: the width-ladder
    bootstrap under ALIGNED timers (bench configuration) must end with
    ONE connected component and converge a broadcast in the validated
    ~20-round envelope.  Factor-8 waves on the upper rungs measured
    6-14 disconnected islands at 100k (BENCH_NOTES r5); the default
    gentle upper rungs must keep this property at CPU scale too."""
    from partisan_tpu.config import Config, PlumtreeConfig
    from partisan_tpu.scenarios import _boot_ladder

    n = 4096
    model = Plumtree()

    def mk(width):
        return Cluster(Config(
            n_nodes=width, seed=1, peer_service_manager="hyparview",
            msg_words=16, partition_mode="groups", max_broadcasts=8,
            inbox_cap=16, emit_compact=32, timer_stagger=False,
            plumtree=PlumtreeConfig(push_slots=2, lazy_cap=4)),
            model=model)

    cl, st = _boot_ladder(mk, n, widths=[1024, n])
    act = np.asarray(st.manager.active)
    alive = np.asarray(st.faults.alive)
    assert len(components(act, alive)) == 1
    st = st._replace(model=model.broadcast(st.model, 0, 0, int(st.rnd)))
    r0 = int(st.rnd)
    st, conv = cl.run_until(
        st, lambda s: float(model.coverage(
            s.model, s.faults.alive, 0)) == 1.0,
        max_rounds=60, check_every=5)
    assert conv != -1 and conv - r0 <= 30, (conv, r0)


# ---------------------------------------------------------------------------
# Chunked soak engine (soak.py): the long-horizon orchestration layer.
#
# The contracts under test, in dependency order:
#  1. chunking is pure composition — soak.run(k, chunk) is bit-identical
#     to one monolithic cluster.steps(state, k), for every chunk size
#     (including 1 and non-divisors), with every observability plane
#     AND the flight recorder riding the carry,
#  2. checkpoints are crash-safe — atomic writes, config fingerprints,
#     corruption and round validation all fail loudly,
#  3. a worker crash mid-chunk (injected JaxRuntimeError) retries from
#     the last checkpoint in a fresh context and lands bit-identically,
#  4. storm timelines are absolute-round-keyed: a resumed run — same
#     process or a fresh engine restoring from disk — replays the
#     identical storm, bit for bit.
# ---------------------------------------------------------------------------


def _planes_cluster(n=32, seed=3):
    """Small hyparview+plumtree cluster with EVERY plane in the carry:
    metrics, latency, health, provenance, and the flight-recorder ring
    (which forces the generic wire path, like capture)."""
    cfg = Config(n_nodes=n, seed=seed, peer_service_manager="hyparview",
                 msg_words=16, partition_mode="groups",
                 metrics=True, metrics_ring=64, latency=True,
                 health=5, health_ring=32,
                 provenance=True, provenance_ring=64,
                 flight_rounds=4)
    return Cluster(cfg, model=Plumtree())


def _booted(cl, settle=20):
    n = cl.cfg.n_nodes
    st = cl.init()
    m = cl.manager.join_many(cl.cfg, st.manager,
                             list(range(1, n)), [0] * (n - 1))
    st = cl.steps(st._replace(manager=m), settle)
    st = st._replace(model=cl.model.broadcast(st.model, 0, 0, int(st.rnd)))
    return cl.steps(st, 5)


def _test_storm(start, period=0):
    """A full fault cycle: drop -> crash batch -> partition -> heal ->
    churn, absolute-round-keyed at `start`."""
    return soak.Storm(events=(
        (0, soak.LinkDrop(0.2)),
        (4, soak.CrashBatch(frac=0.05)),
        (8, soak.Partition()),
        (12, soak.Heal(revive=True)),
        (16, soak.Churn(0.02, 0.02)),
    ), start=start, period=period)


def test_chunked_run_bit_identical_across_chunk_sizes():
    """soak.run(k, chunk) == cluster.steps(state, k) bit-for-bit, with
    all planes + flight enabled, for chunk=1 and a non-divisor chunk —
    plus the Cluster.run_chunked front door.  k matches _booted's
    settle length so the monolithic reference reuses its compiled scan
    (tier-1 compile budget)."""
    cl = _planes_cluster()
    st = _booted(cl)
    k = 20
    ref = cl.steps(st, k)
    for chunk in (1, 7):
        got = soak.run(cl, st, k, chunk=chunk)
        assert_states_bitidentical(got, ref, f"chunk={chunk}")
    got = cl.run_chunked(st, k, chunk=7)
    assert_states_bitidentical(got, ref, "run_chunked")


@pytest.mark.slow
def test_chunked_storm_parity_and_event_boundaries():
    """A chunked storm run equals the unchunked reference composition
    (one uncapped scan per storm gap), and no chunk ever crosses a
    storm event round — the boundary discipline that makes host-side
    fault actions land at exactly their scheduled round."""
    cl = _planes_cluster()
    st = _booted(cl)
    r0 = int(jax.device_get(st.rnd))
    storm = _test_storm(r0, period=20)
    eng = soak.Soak(make_cluster=lambda: cl, storm=storm,
                    invariants=[soak.conservation()],
                    cfg=soak.SoakConfig(chunk_fixed=7))
    res = eng.run(st, rounds=50)
    assert res.rounds == 50 and res.breaches == 0
    ref = soak.reference_run(cl, st, r0 + 50, storm=storm)
    assert_states_bitidentical(res.state, ref, "storm_chunked_vs_ref")
    # boundary discipline: event rounds only ever START a chunk
    event_rounds = set()
    r = r0
    while r < r0 + 50:
        nxt = storm.next_after(r)
        if nxt is None or nxt >= r0 + 50:
            break
        event_rounds.add(nxt)
        r = nxt
    for row in res.chunks:
        for ev in event_rounds:
            assert not (row["round"] < ev < row["round"] + row["k"]), \
                (row, ev)
    # the health digest rode along: every chunk row polled it
    assert all("digest" in row for row in res.chunks)


def test_storm_omission_installs_filibuster_schedule():
    """The Omission action re-encodes absolute-round drops into the
    builder schedule's frame: the installed window must actually
    suppress sends (vs the storm-free run), stay chunk-parity with the
    unchunked reference, and a mis-anchored window must raise instead
    of silently dropping nothing."""
    from partisan_tpu import interpose
    from partisan_tpu.models.anti_entropy import AntiEntropy

    n = 8
    cfg = Config(n_nodes=n, seed=2, inbox_cap=32)
    model = AntiEntropy()
    total = 64   # builder window: absolute rounds [0, 64)

    def mk():
        return Cluster(cfg, model=model,
                       interpose=interpose.OmissionSchedule(
                           np.zeros((total, n, 64), np.bool_), start=0))

    cl = mk()
    st = cl.init()
    m = st.manager
    for i in range(1, n):
        m = cl.manager.join(cfg, m, i, 0)
    st = cl.steps(st._replace(manager=m), 10)
    st = st._replace(model=model.broadcast(st.model, 0, 0))
    r0 = int(jax.device_get(st.rnd))

    drops = np.ones((4, n, 64), np.bool_)   # omit EVERY send, 4 rounds
    storm = soak.Storm(events=((2, soak.Omission(drops, start=r0 + 2)),),
                       start=r0)
    eng = soak.Soak(make_cluster=mk, storm=storm,
                    cfg=soak.SoakConfig(chunk_fixed=5))
    res = eng.run(st, rounds=20)
    ref = soak.reference_run(mk(), st, r0 + 20, storm=storm)
    assert_states_bitidentical(res.state, ref, "omission_storm")
    # the schedule bit: the blackout window cost real deliveries
    base = cl.steps(st, 20)
    assert int(jax.device_get(res.state.stats.delivered)) \
        < int(jax.device_get(base.stats.delivered))
    # a window outside the builder's frame fails loudly
    bad = soak.Storm(events=(
        (0, soak.Omission(drops, start=total + 10)),), start=r0)
    with pytest.raises(ValueError, match="outside the cluster schedule"):
        soak.Soak(make_cluster=mk, storm=bad,
                  cfg=soak.SoakConfig(chunk_fixed=5)).run(st, rounds=5)
    # two Omissions MERGE: the second must not erase the first's
    # still-pending rows (host-level, no stepping)
    one = np.zeros((1, n, 64), np.bool_)
    one[0, 3, 0] = True
    s2 = soak.Omission(one, start=10).apply(cl, cl.init(), 0)
    s2 = soak.Omission(one, start=30).apply(cl, s2, 0)
    merged = np.asarray(jax.device_get(s2.interpose))
    assert merged[10, 3, 0] and merged[30, 3, 0]


def test_mid_storm_restore_replays_controller_decisions(tmp_path):
    """ISSUE 10 soak interplay: with all three in-scan controllers in
    the carry, a worker crash mid-STORM (retry + fresh context +
    checkpoint restore) must replay every controller decision
    bit-identically — the final state, CONTROLLER LEAVES INCLUDED
    (eager-cap trajectory rings, pressure integrators, heal boost),
    equals the undisturbed storm run's.  Controllers are pure functions
    of the carry, so the checkpoint protocol that replays the storm
    replays the loop; this extends the storm-replay parity suite to
    the closed-loop round."""
    from partisan_tpu.config import ControlConfig

    def mk():
        cfg = Config(n_nodes=32, seed=3, peer_service_manager="hyparview",
                     msg_words=16, partition_mode="groups",
                     metrics=True, metrics_ring=64, latency=True,
                     health=5, health_ring=32,
                     provenance=True, provenance_ring=64,
                     channel_capacity=True,
                     control=ControlConfig(fanout=True, backpressure=True,
                                           healing=True, ring=16))
        return Cluster(cfg, model=Plumtree())

    cl = mk()
    st = _booted(cl)
    r0 = int(jax.device_get(st.rnd))
    storm = _test_storm(r0, period=20)   # the storm drives escalation
    crashed = {"done": False}

    def step(c, s, k):
        r = int(jax.device_get(s.rnd))
        if not crashed["done"] and r + k > r0 + 25:
            crashed["done"] = True
            raise jax.errors.JaxRuntimeError("injected worker crash")
        return c.steps(s, k)

    eng = soak.Soak(
        make_cluster=mk, storm=storm, step_fn=step,
        cfg=soak.SoakConfig(chunk_fixed=10, cooldown_s=0.0,
                            checkpoint_dir=str(tmp_path),
                            degraded_factor=1e9),
        sleep_fn=lambda s: None)
    res = eng.run(st, rounds=40)
    assert res.retries == 1 and crashed["done"]
    # controller operands surfaced on every chunk row (the soak_report
    # surface of the decision state)
    assert all("control" in row for row in res.chunks)
    ref = soak.reference_run(mk(), st, r0 + 40, storm=storm)
    # the storm crashed nodes and degraded the digest: the healing
    # loop must actually have acted for this parity to mean anything
    assert int(ref.control.healing.adjustments) >= 1
    assert_states_bitidentical(res.state, ref, "control_storm_resume")


def test_kill_at_chunk_boundary_resume_bit_parity(tmp_path):
    """An injected JaxRuntimeError mid-run triggers retry-with-backoff:
    cool down, rebuild the cluster (fresh context), restore the last
    checkpoint, replay — and the final state is bit-identical to the
    undisturbed run.  The recovery path emits chunk_retry +
    checkpoint_restored (log and live bus), and on-disk checkpoints
    appear at the chunk boundaries."""
    def mk():
        return _planes_cluster()

    cl = mk()
    st = _booted(cl)
    r0 = int(jax.device_get(st.rnd))
    crashed = {"done": False}

    def step(c, s, k):
        r = int(jax.device_get(s.rnd))
        if not crashed["done"] and r + k > r0 + 25:
            crashed["done"] = True
            raise jax.errors.JaxRuntimeError("injected worker crash")
        return c.steps(s, k)

    rec = telemetry.Recorder()
    bus = telemetry.Bus()
    bus.attach("t", ("partisan", "soak"), rec)
    slept = []
    eng = soak.Soak(
        make_cluster=mk, step_fn=step, bus=bus,
        cfg=soak.SoakConfig(chunk_fixed=10, cooldown_s=0.25,
                            checkpoint_dir=str(tmp_path),
                            degraded_factor=1e9),
        sleep_fn=slept.append)
    res = eng.run(st, rounds=40)
    assert res.retries == 1 and crashed["done"]
    kinds = [e["kind"] for e in res.log]
    assert kinds.count("chunk_retry") == 1
    assert kinds.count("checkpoint_restored") == 1
    assert slept == [0.25]          # backoff consulted the cool-down
    assert [e[0] for e in rec.events] == [
        telemetry.SOAK_CHUNK_RETRY, telemetry.SOAK_CHECKPOINT_RESTORED]
    assert checkpoint.steps(tmp_path)[0] == r0
    ref = cl.steps(st, 40)
    assert_states_bitidentical(res.state, ref, "crash_resume")


@pytest.mark.slow
def test_fresh_process_resume_replays_storm(tmp_path):
    """The whole-process restart path: engine A soaks partway through a
    storm and is discarded; engine B — new cluster, new (identically
    declared) storm — resumes from the newest on-disk checkpoint and
    finishes.  The result is bit-identical to the uninterrupted
    unchunked composition: the timeline is absolute-round-keyed and the
    checkpoint-before-actions protocol re-applies the boundary's due
    actions on resume, so the storm replays exactly."""
    def mk():
        return _planes_cluster()

    cl = mk()
    st = _booted(cl)
    r0 = int(jax.device_get(st.rnd))

    eng_a = soak.Soak(make_cluster=mk, storm=_test_storm(r0, period=20),
                      cfg=soak.SoakConfig(chunk_fixed=8,
                                          checkpoint_dir=str(tmp_path)))
    eng_a.run(st, rounds=24)

    eng_b = soak.Soak(make_cluster=mk, storm=_test_storm(r0, period=20),
                      cfg=soak.SoakConfig(chunk_fixed=8,
                                          checkpoint_dir=str(tmp_path)))
    res = eng_b.run(resume=True, until_round=r0 + 56)
    ref = soak.reference_run(mk(), st, r0 + 56,
                             storm=_test_storm(r0, period=20))
    assert_states_bitidentical(res.state, ref, "fresh_process_resume")


@pytest.mark.slow
def test_degraded_worker_detection_cools_down_and_rebuilds():
    """Sustained post-crash slowness trips the degraded-worker path:
    the first post-rebuild chunk is exempt (it pays re-trace/compile —
    no evidence), the NEXT chunk is judged against the pre-crash
    baseline (MINUTE_FAULT: ~20x measured, steady) — logged, cooled
    down longer, rebuilt and re-run until the worker recovers."""
    import time as time_mod

    cl = Cluster(hv_config(16, seed=5), model=Plumtree())
    st = _booted(cl, settle=10)
    r0 = int(jax.device_get(st.rnd))
    # slow for TWO chunks after the crash: the exempt rebuild chunk and
    # the probation chunk that convicts
    state = {"crash_at": r0 + 30, "crashed": False, "slow_left": 2}

    def step(c, s, k):
        r = int(jax.device_get(s.rnd))
        if not state["crashed"] and r + k > state["crash_at"]:
            state["crashed"] = True
            raise jax.errors.JaxRuntimeError("injected worker crash")
        out = c.steps(s, k)
        int(jax.device_get(out.rnd))
        if state["crashed"] and state["slow_left"] > 0:
            state["slow_left"] -= 1
            time_mod.sleep(2.0)      # the degraded worker: >>20x a warm
            #                          CPU chunk of 5 rounds
        return out

    slept = []
    eng = soak.Soak(make_cluster=lambda: cl, step_fn=step,
                    cfg=soak.SoakConfig(chunk_fixed=5, cooldown_s=0.5,
                                        degraded_factor=20.0,
                                        max_retries=4),
                    sleep_fn=slept.append)
    res = eng.run(st, rounds=50)
    assert res.rounds == 50
    degraded = [e for e in res.log if e.get("degraded")]
    assert len(degraded) == 1, res.log
    # backoff doubled for the degraded retry (attempt 2 after the crash)
    assert slept == [0.5, 1.0]
    ref = cl.steps(st, 50)
    assert_states_bitidentical(res.state, ref, "degraded_recovery")


def test_retries_exhausted_raises():
    cl = _small_cluster()    # shares the checkpoint tests' programs
    st = _booted(cl, settle=5)

    def step(c, s, k):
        raise jax.errors.JaxRuntimeError("permanently down")

    eng = soak.Soak(make_cluster=lambda: cl, step_fn=step,
                    cfg=soak.SoakConfig(chunk_fixed=5, cooldown_s=0.0,
                                        max_retries=2),
                    sleep_fn=lambda s: None)
    with pytest.raises(RuntimeError, match="retries exhausted"):
        eng.run(st, rounds=10)


def test_invariant_breach_dumps_black_box(tmp_path):
    """A breached invariant logs partisan.soak.invariant_breach and
    dumps the black box: the flight ring decoded to a replayable trace
    plus every enabled plane's snapshot — and the dedup guard logs one
    breach per (round, invariant), not one per retry visit."""
    from partisan_tpu import trace as trace_mod

    cl = _planes_cluster()   # same shape as the kill test: programs shared
    st = _booted(cl)
    always = soak.Invariant(
        "always_red", lambda c, s: (False, {"why": "test"}))
    eng = soak.Soak(make_cluster=lambda: cl, invariants=[always],
                    cfg=soak.SoakConfig(chunk_fixed=10,
                                        dump_dir=str(tmp_path)))
    res = eng.run(st, rounds=20)
    breaches = [e for e in res.log if e["kind"] == "invariant_breach"]
    # one per boundary (start, 2 interior-ends... final): 3 boundaries
    assert len(breaches) == res.breaches == 3
    assert len({e["round"] for e in breaches}) == 3
    for e in breaches:
        assert e["invariant"] == "always_red"
        assert e["dumps"], "no black-box dumps recorded"
        for p in e["dumps"]:
            assert os.path.exists(p), p
    flight = [p for p in breaches[0]["dumps"] if p.endswith("_flight.npz")]
    assert flight, "flight ring not dumped"
    tr = trace_mod.Trace.load(flight[0])
    assert tr.n_rounds == cl.cfg.flight_rounds


def test_replay_soak_events_synthetic_log():
    log = [
        {"kind": "chunk_retry", "round": 7, "k": 10, "attempt": 1,
         "cooldown_s": 1.0, "error": "boom"},
        {"kind": "checkpoint_restored", "round": 5, "source": "/tmp/x"},
        {"kind": "invariant_breach", "round": 9,
         "invariant": "conservation", "info": {"emitted": 3},
         "dumps": []},
        {"kind": "chunk", "round": 0, "k": 10},      # not an event
    ]
    rec = telemetry.Recorder()
    bus = telemetry.Bus()
    bus.attach("t", ("partisan", "soak"), rec)
    assert telemetry.replay_soak_events(bus, log) == 3
    events = [e[0] for e in rec.events]
    assert events == [telemetry.SOAK_CHUNK_RETRY,
                      telemetry.SOAK_CHECKPOINT_RESTORED,
                      telemetry.SOAK_INVARIANT_BREACH]
    retry = rec.of(telemetry.SOAK_CHUNK_RETRY)[0]
    assert retry[1]["attempt"] == 1 and retry[2]["round"] == 7
    breach = rec.of(telemetry.SOAK_INVARIANT_BREACH)[0]
    assert breach[2]["invariant"] == "conservation"
    assert breach[2]["round"] == 9


# ---------------------------------------------------------------------------
# Hardened checkpoints (checkpoint.py): the soak engine's persistence
# layer must fail loudly on every corruption the crash cycle can cause.
# ---------------------------------------------------------------------------


def _small_cluster(seed=5):
    return Cluster(hv_config(24, seed=seed), model=Plumtree())


def test_checkpoint_atomic_write_leaves_no_temp_files(tmp_path):
    cl = _small_cluster()
    st = cl.steps(_booted(cl, settle=5), 3)
    p = tmp_path / "ck.npz"
    checkpoint.save(st, p, cfg=cl.cfg)
    assert p.exists()
    leftovers = [f for f in os.listdir(tmp_path) if ".tmp." in f]
    assert not leftovers, leftovers
    back = checkpoint.restore(p, like=cl.init(), cfg=cl.cfg)
    assert_states_bitidentical(back, st, "atomic_roundtrip")


def test_checkpoint_fingerprint_rejects_shape_preserving_drift(tmp_path):
    """A config change that keeps every leaf shape (here: the seed) is
    invisible to the structural check — the fingerprint must catch
    it."""
    cl = _small_cluster(seed=5)
    st = _booted(cl, settle=5)
    p = tmp_path / "ck.npz"
    checkpoint.save(st, p, cfg=cl.cfg)
    drifted = _small_cluster(seed=6)
    with pytest.raises(checkpoint.CheckpointError, match="fingerprint"):
        checkpoint.restore(p, like=drifted.init(), cfg=drifted.cfg)
    # without the fingerprint cross-check the structural check alone
    # accepts it — the gap the fingerprint closes
    checkpoint.restore(p, like=drifted.init())


def test_checkpoint_truncated_file_raises_clear_error(tmp_path):
    cl = _small_cluster()
    st = _booted(cl, settle=5)
    p = tmp_path / "ck.npz"
    checkpoint.save(st, p, cfg=cl.cfg)
    raw = p.read_bytes()
    p.write_bytes(raw[: len(raw) // 2])
    with pytest.raises(checkpoint.CheckpointError,
                       match="corrupt or truncated"):
        checkpoint.restore(p, like=cl.init(), cfg=cl.cfg)
    # garbage (not even a zip) is the same clear failure, not a
    # BadZipFile traceback
    p.write_bytes(b"not a checkpoint at all")
    with pytest.raises(checkpoint.CheckpointError,
                       match="corrupt or truncated"):
        checkpoint.restore(p, like=cl.init())


def test_checkpoint_round_validation(tmp_path):
    cl = _small_cluster()
    st = _booted(cl, settle=5)
    rnd = int(jax.device_get(st.rnd))
    p = tmp_path / "ck.npz"
    checkpoint.save(st, p, cfg=cl.cfg)
    checkpoint.restore(p, like=cl.init(), expect_rnd=rnd)
    with pytest.raises(checkpoint.CheckpointError, match="expected round"):
        checkpoint.restore(p, like=cl.init(), expect_rnd=rnd + 1)


def test_restore_latest_falls_back_past_corrupt_newest(tmp_path):
    """A torn newest checkpoint (OS crash publishing torn bytes) must
    not permanently block resume: restore_latest falls back to the
    next-older intact file; with every file corrupt it raises the
    corruption error rather than returning None (which would silently
    restart the soak from scratch)."""
    cl = _small_cluster()
    st = _booted(cl, settle=5)
    checkpoint.save_step(st, tmp_path, int(jax.device_get(st.rnd)),
                         cfg=cl.cfg)
    st2 = cl.steps(st, 5)
    r2 = int(jax.device_get(st2.rnd))
    p2 = checkpoint.save_step(st2, tmp_path, r2, cfg=cl.cfg)
    with open(p2, "r+b") as f:
        f.truncate(64)
    back = checkpoint.restore_latest(tmp_path, cl.init(), cfg=cl.cfg)
    assert int(jax.device_get(back.rnd)) == int(jax.device_get(st.rnd))
    assert_states_bitidentical(back, st, "fallback_restore")
    for rnd in checkpoint.steps(tmp_path):
        with open(tmp_path / f"ckpt_{rnd}.npz", "r+b") as f:
            f.truncate(64)
    with pytest.raises(checkpoint.CheckpointCorruptError,
                       match="every checkpoint"):
        checkpoint.restore_latest(tmp_path, cl.init(), cfg=cl.cfg)


def test_checkpoint_v1_files_still_restore(tmp_path):
    """Format-1 checkpoints (leaves only, pre-hardening) restore
    without the new validation — old soak artifacts stay readable."""
    cl = _small_cluster()
    st = _booted(cl, settle=5)
    leaves = jax.tree.leaves(st)
    p = tmp_path / "legacy.npz"
    np.savez_compressed(p, version=1, n_leaves=len(leaves),
                        **{f"leaf_{i}": np.asarray(x)
                           for i, x in enumerate(leaves)})
    back = checkpoint.restore(p, like=cl.init(), cfg=cl.cfg)
    assert_states_bitidentical(back, st, "v1_compat")


# ---------------------------------------------------------------------------
# The acceptance gate: thousands of rounds under a repeating storm,
# crash-surviving, bit-identical to the unchunked composition.
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_soak_2000_rounds_repeating_storm_crash_surviving(tmp_path):
    """ISSUE 7 acceptance: a >=2000-round soak under a repeating fault
    storm completes via chunked execution (every chunk <= 1000 rounds),
    is bit-identical to the equivalent unchunked composition, and —
    with a worker crash injected mid-run — resumes from checkpoint with
    the storm timeline replaying identically across the restart."""
    def mk():
        return Cluster(Config(
            n_nodes=64, seed=11, peer_service_manager="hyparview",
            msg_words=16, partition_mode="groups",
            health=10, health_ring=64), model=Plumtree())

    cl = mk()
    st = _booted(cl)
    r0 = int(jax.device_get(st.rnd))
    rounds = 2000
    storm = soak.Storm(events=(
        (0, soak.LinkDrop(0.2)),
        (40, soak.Heal()),
        (60, soak.CrashBatch(frac=0.02)),
        (100, soak.Partition()),
        (140, soak.Heal(revive=True)),
        (160, soak.Churn(0.01, 0.01)),
        (180, soak.Heal(revive=True)),
    ), start=r0, period=200)

    crashed = {"done": False}

    def step(c, s, k):
        r = int(jax.device_get(s.rnd))
        if not crashed["done"] and r + k > r0 + 1100:
            crashed["done"] = True
            raise jax.errors.JaxRuntimeError("injected worker crash")
        return c.steps(s, k)

    eng = soak.Soak(
        make_cluster=mk, storm=storm, step_fn=step,
        invariants=[soak.conservation()],
        cfg=soak.SoakConfig(chunk_fixed=500, cooldown_s=0.0,
                            checkpoint_every=200,
                            checkpoint_dir=str(tmp_path),
                            degraded_factor=1e9),
        sleep_fn=lambda s: None)
    res = eng.run(st, rounds=rounds)
    assert res.rounds == rounds
    assert crashed["done"] and res.retries == 1
    assert all(row["k"] <= 1000 for row in res.chunks)
    assert res.breaches == 0            # conservation held throughout
    ref = soak.reference_run(mk(), st, r0 + rounds, storm=storm)
    assert_states_bitidentical(res.state, ref, "acceptance_2000")


def test_script_action_pure_replay_under_restore(tmp_path):
    """soak.Script (the escape-hatch action, previously only exercised
    indirectly): a scripted pure transform fires at its absolute
    round, and a worker crash that rewinds PAST it re-applies it
    identically — the final state matches the unchunked reference
    composition bit for bit."""
    def mk():
        return Cluster(Config(n_nodes=24, seed=7,
                              peer_service_manager="hyparview",
                              msg_words=16, partition_mode="groups"),
                       model=Plumtree())

    def crash_3(cluster, state, rnd):
        return state._replace(
            faults=faults_mod.crash(state.faults, 3))

    cl = mk()
    st = _booted(cl)
    r0 = int(jax.device_get(st.rnd))
    storm = soak.Storm(events=(
        (5, soak.Script(crash_3)),
        (15, soak.Heal(revive=True)),
    ), start=r0)
    crashed = {"done": False}

    def step(c, s, k):
        r = int(jax.device_get(s.rnd))
        # crash the dispatch AFTER the Script round: the restore
        # rewinds to the round-r0+5 checkpoint and must re-apply it
        if not crashed["done"] and r + k > r0 + 10:
            crashed["done"] = True
            raise jax.errors.JaxRuntimeError("injected worker crash")
        return c.steps(s, k)

    eng = soak.Soak(make_cluster=mk, storm=storm, step_fn=step,
                    cfg=soak.SoakConfig(chunk_fixed=5, cooldown_s=0.0,
                                        checkpoint_dir=str(tmp_path)),
                    sleep_fn=lambda s: None)
    res = eng.run(st, rounds=30)
    assert res.retries == 1 and crashed["done"]
    ref = soak.reference_run(mk(), st, r0 + 30, storm=storm)
    assert_states_bitidentical(res.state, ref, "script_replay")
    # the scripted crash actually happened, then the heal revived
    assert bool(np.asarray(res.state.faults.alive)[3])


def test_omission_merge_idempotent_under_restore(tmp_path):
    """Omission actions MERGE (OR) into the installed schedule: two
    overlapping windows compose as the union, and a crash-retry that
    re-applies a due Omission on restore is idempotent — the final
    state (schedule leaf included) matches the unchunked reference."""
    from partisan_tpu import interpose

    n, E = 16, 80
    sched = interpose.OmissionSchedule(
        np.zeros((60, n, E), np.bool_), start=0)

    def mk():
        return Cluster(Config(n_nodes=n, seed=9,
                              peer_service_manager="hyparview",
                              msg_words=16, partition_mode="groups"),
                       model=Plumtree(), interpose=sched)

    cl = mk()
    st = _booted(cl)
    r0 = int(jax.device_get(st.rnd))
    assert r0 + 20 <= 60, "size the builder window over the horizon"

    def drops(lo, hi, node):
        d = np.zeros((hi - lo, n, E), np.bool_)
        d[:, node, :] = True
        return d

    storm = soak.Storm(events=(
        # overlapping windows for nodes 0 and 1: the second action
        # must not erase the first's still-pending rows
        (2, soak.Omission(drops(r0 + 2, r0 + 12, 0), start=r0 + 2)),
        (4, soak.Omission(drops(r0 + 4, r0 + 14, 1), start=r0 + 4)),
    ), start=r0)
    crashed = {"done": False}

    def step(c, s, k):
        r = int(jax.device_get(s.rnd))
        # rewind lands ON an Omission boundary: the restore re-applies
        # the due action over a schedule that already contains it
        if not crashed["done"] and r + k > r0 + 6:
            crashed["done"] = True
            raise jax.errors.JaxRuntimeError("injected worker crash")
        return c.steps(s, k)

    eng = soak.Soak(make_cluster=mk, storm=storm, step_fn=step,
                    cfg=soak.SoakConfig(chunk_fixed=2, cooldown_s=0.0,
                                        checkpoint_dir=str(tmp_path)),
                    sleep_fn=lambda s: None)
    res = eng.run(st, rounds=20)
    assert res.retries == 1 and crashed["done"]
    ref = soak.reference_run(mk(), st, r0 + 20, storm=storm)
    assert_states_bitidentical(res.state, ref, "omission_merge")
    # the merged schedule holds BOTH windows (union, not overwrite)
    final = np.asarray(jax.device_get(res.state.interpose))
    assert final[r0 + 6 - 0, 0].all() and final[r0 + 6 - 0, 1].all()
    # direct idempotence: re-applying the same action changes nothing
    again = storm.events[0][1].apply(mk(), res.state, r0 + 2)
    assert np.array_equal(np.asarray(jax.device_get(again.interpose)),
                          final)


# ---------------------------------------------------------------------------
# Pipelined chunk dispatch (ISSUE 18): pipeline_depth >= 2 submits
# chunk i+1 before blocking on chunk i inside boundary-free stretches.
# The contracts: bit parity with the synchronous engine under a
# crash+partition storm (boundary work only ever runs on a drained
# pipeline), in-flight chunks that die re-dispatch from the last
# synchronized carry without double-counting, and donated carries are
# barriered through a derived probe so per-row polls never read
# donated-away buffers.
# ---------------------------------------------------------------------------


def test_pipelined_soak_bit_parity_crash_partition_storm(tmp_path):
    """Depth-2 pipelined soak under the full fault cycle with a worker
    kill injected while a chunk is in flight: the crash drops the whole
    pipeline, rewinds to the last synchronized checkpoint, and the
    final state is bit-identical to the unchunked storm reference.
    Replayed rows reconcile exactly: sum(k) == rounds run, and the
    overlapped rows carry clamped true-stall gaps."""
    from partisan_tpu import perfwatch

    def mk():
        return _planes_cluster()

    cl = mk()
    st = _booted(cl)
    r0 = int(jax.device_get(st.rnd))
    storm = _test_storm(r0, period=0)
    crashed = {"done": False}

    def step(c, s, k):
        r = int(jax.device_get(s.rnd))
        # fires while the previous chunk of the stretch is in flight:
        # the pipeline (not just one dispatch) must rewind
        if not crashed["done"] and r + k > r0 + 30:
            crashed["done"] = True
            raise jax.errors.JaxRuntimeError("injected worker crash")
        return c.steps(s, k)

    eng = soak.Soak(
        make_cluster=mk, storm=storm, step_fn=step,
        cfg=soak.SoakConfig(chunk_fixed=5, pipeline_depth=2,
                            checkpoint_every=10, cooldown_s=0.0,
                            checkpoint_dir=str(tmp_path),
                            degraded_factor=1e9),
        sleep_fn=lambda s: None)
    res = eng.run(st, rounds=40)
    assert res.retries == 1 and crashed["done"]
    kinds = [e["kind"] for e in res.log]
    assert kinds.count("chunk_retry") == 1
    assert kinds.count("checkpoint_restored") == 1
    # rows reconcile across the mid-pipeline rewind: no double-count
    assert sum(row["k"] for row in res.chunks) == res.rounds == 40
    # the pipeline actually overlapped (some row submitted before the
    # previous chunk's ready), and its gap is a clamped true stall
    piped = [row for row in res.chunks if row.get("pipelined")]
    assert piped and all(row["gap_s"] == 0.0 for row in piped)
    assert all(row.get("gap_s", 0.0) >= 0.0 for row in res.chunks)
    d = perfwatch.decompose_chunks(res.chunks)
    assert d["overlapped_chunks"] == len(piped) and d["gap_s"] >= 0.0
    ref = soak.reference_run(mk(), st, r0 + 40, storm=storm)
    assert_states_bitidentical(res.state, ref, "pipelined_storm_crash")


def test_pipelined_donated_carry_probe_and_poll_gating():
    """A donating cluster under depth-2 pipelining: the engine barriers
    in-flight chunks through a derived round probe (their carry buffers
    are donated to the next dispatch), skips per-row plane polls for
    exactly those rows, polls the stretch-final rows as always — and
    the run is bit-identical to the plain synchronous engine's."""
    cfg = _planes_cluster().cfg
    cl_plain = Cluster(cfg, model=Plumtree())
    st = _booted(cl_plain)
    r0 = int(jax.device_get(st.rnd))
    ref = soak.reference_run(cl_plain, st, r0 + 20)

    eng = soak.Soak(
        make_cluster=lambda: Cluster(cfg, model=Plumtree(), donate=True),
        cfg=soak.SoakConfig(chunk_fixed=5, pipeline_depth=2,
                            checkpoint_every=10))
    res = eng.run(st, rounds=20)
    assert res.rounds == 20
    assert_states_bitidentical(res.state, ref, "pipelined_donated")
    # stretches are 10 rounds = 2 chunks: the first of each pair was
    # donated away (no polls), the stretch-final one polled as always
    assert [row["k"] for row in res.chunks] == [5, 5, 5, 5]
    assert "digest" not in res.chunks[0] and "digest" in res.chunks[1]
    assert "digest" not in res.chunks[2] and "digest" in res.chunks[3]
    assert res.chunks[1].get("pipelined") and res.chunks[3].get("pipelined")
