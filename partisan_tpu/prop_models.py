"""System models for the property harness.

Mirrors the reference's model zoo: ``prop_partisan_noop.erl`` (78 LoC),
``prop_partisan_reliable_broadcast.erl`` (389), ``prop_partisan_
primary_backup.erl`` (388); the application-under-test models (hbbft,
paxoid, zraft, riak_ensemble, lashup) are external apps and out of scope
— the corpus equivalents here run against models/ protocols.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Any

from partisan_tpu.cluster import Cluster
from partisan_tpu.config import Config
from partisan_tpu.models.alsberg_day import AlsbergDay
from partisan_tpu.models.direct_mail import DirectMail
from partisan_tpu.prop import Command


def _boot_fullmesh(cl: Cluster, settle: int = 15):
    st = cl.init()
    m = st.manager
    for i in range(1, cl.cfg.n_nodes):
        m = cl.manager.join(cl.cfg, m, i, 0)
    st = st._replace(manager=m)
    return cl.steps(st, settle)


def _cached_build(self, make):
    """Boot once, reuse the (immutable) booted state for every run —
    determinism makes re-booting equivalent to state reuse, and sharing
    the Cluster keeps one jit cache across runs/shrinks."""
    if not hasattr(self, "_cl"):
        self._cl = make()
        self._st0 = _boot_fullmesh(self._cl)
    return self._cl, self._st0


@dataclasses.dataclass
class NoopSystem:
    """prop_partisan_noop.erl: no commands beyond sync; vacuous
    postcondition — exercises the harness itself."""

    n_nodes: int = 4
    seed: int = 0
    name: str = "noop"

    def build(self):
        return _cached_build(self, lambda: Cluster(
            Config(n_nodes=self.n_nodes, seed=self.seed,
                   inbox_cap=max(32, self.n_nodes + 8))))

    def gen_command(self, rng: random.Random, cl, st) -> Command:
        return Command(name="sync", args=(), apply=lambda c, s: s)

    def postcondition(self, cl, st, script) -> bool:
        return True

    def settle_rounds(self) -> int:
        return 2


@dataclasses.dataclass
class ReliableBroadcastSystem:
    """prop_partisan_reliable_broadcast.erl: random nodes broadcast; the
    property is agreement — every alive node delivers every broadcast
    message.  ``acked=True`` (retransmission) satisfies it under transient
    omissions; the unacked variant is the harness's canary."""

    n_nodes: int = 6
    seed: int = 0
    acked: bool = True
    name: str = "reliable_broadcast"

    def __post_init__(self):
        self.model = DirectMail(acked=self.acked)
        self._next_slot = 0

    def build(self):
        return _cached_build(self, lambda: Cluster(
            Config(n_nodes=self.n_nodes, seed=self.seed,
                   inbox_cap=max(32, self.n_nodes + 8),
                   ack_cap=16 if self.acked else 0),
            model=self.model))

    def gen_command(self, rng: random.Random, cl, st) -> Command:
        node = rng.randrange(self.n_nodes)
        slot = self._next_slot % cl.cfg.max_broadcasts
        self._next_slot += 1
        return Command(
            name="broadcast", args=(node, slot),
            apply=lambda c, s, _n=node, _sl=slot: s._replace(
                model=self.model.broadcast(s.model, _n, _sl)))

    def postcondition(self, cl, st, script) -> bool:
        # Delivery is asserted for broadcasts whose origin stayed correct
        # (never crashed): a crashed origin may not even have sent, and
        # the reference model likewise only constrains correct nodes
        # (prop_partisan_reliable_broadcast.erl postconditions).
        issued = [c.args for c in script if c.name == "broadcast"]
        alive = st.faults.alive
        for (node, slot) in issued:
            if not bool(alive[node]):
                continue
            if float(self.model.coverage(st.model, alive, slot)) != 1.0:
                return False
        return True

    def settle_rounds(self) -> int:
        return 12


@dataclasses.dataclass
class LinearizabilitySystem:
    """prop_partisan_linearizability.erl over a single replicated
    register (Alsberg-Day key 0): clients write distinct values, commands
    are issued sequentially, and the property is that the final
    replicated value is the value of the LAST acknowledged write — any
    earlier value surfacing at the end would be a non-linearizable
    history (a lost or reordered overwrite)."""

    n_nodes: int = 5
    seed: int = 0
    name: str = "linearizability"

    def __post_init__(self):
        self.model = AlsbergDay(acked=True, keys=1)
        self._next = 0

    def build(self):
        return _cached_build(self, lambda: Cluster(
            Config(n_nodes=self.n_nodes, seed=self.seed,
                   inbox_cap=max(48, 8 * self.n_nodes),
                   emit_cap=16, ack_cap=32),
            model=self.model))

    def gen_command(self, rng: random.Random, cl, st) -> Command:
        client = rng.randrange(1, self.n_nodes)
        val = 1000 + self._next
        self._next += 1
        return Command(
            name="write", args=(client, 0, val),
            apply=lambda c, s, _c=client, _v=val: s._replace(
                model=self.model.write(s.model, _c, 0, _v)))

    def postcondition(self, cl, st, script) -> bool:
        import numpy as np

        alive = st.faults.alive
        writes = [c.args for c in script if c.name == "write"]
        if not writes:
            return True
        if not bool(self.model.replicated(st.model, 0, alive)):
            return False
        # Final-state evidence is lossy: req_ok reflects only each
        # client's LATEST write (an earlier acked write's evidence is
        # reset by a later one), so the value check is made only when it
        # is sound — when the GLOBALLY LAST issued write is acked, it is
        # the unique linearization winner and must be the final value.
        last_client, _k, last_val = writes[-1]
        if bool(self.model.acked_ok(st.model, last_client, 0)):
            final = int(np.asarray(st.model.store)[0, 0])
            return final == last_val
        # Last write unacked: require liveness for correct clients —
        # a surviving client's latest write must eventually ack.
        for (cl_, _k, _v) in writes:
            latest = [w for w in writes if w[0] == cl_][-1]
            if latest[2] != _v:
                continue                     # superseded by a later write
            if bool(alive[cl_]) and \
                    not bool(self.model.acked_ok(st.model, cl_, 0)):
                return False
        return True

    def settle_rounds(self) -> int:
        return 15


@dataclasses.dataclass
class PrimaryBackupSystem:
    """prop_partisan_primary_backup.erl over the Alsberg-Day protocol:
    random clients write; the property is that every write is acked to
    its client AND replicated identically on every alive node."""

    n_nodes: int = 5
    seed: int = 0
    acked: bool = True
    keys: int = 8
    name: str = "primary_backup"

    def __post_init__(self):
        self.model = AlsbergDay(acked=self.acked, keys=self.keys)
        self._next = 0

    def build(self):
        return _cached_build(self, lambda: Cluster(
            Config(n_nodes=self.n_nodes, seed=self.seed,
                   inbox_cap=max(48, 8 * self.n_nodes),
                   emit_cap=16,
                   ack_cap=32 if self.acked else 0),
            model=self.model))

    def gen_command(self, rng: random.Random, cl, st) -> Command:
        client = rng.randrange(1, self.n_nodes)   # node 0 is the primary
        key = self._next % self.keys
        val = 100 + self._next
        self._next += 1
        return Command(
            name="write", args=(client, key, val),
            apply=lambda c, s, _c=client, _k=key, _v=val: s._replace(
                model=self.model.write(s.model, _c, _k, _v)))

    def postcondition(self, cl, st, script) -> bool:
        # Last write per (client, key) must be acked; every written key
        # must be identically replicated across alive nodes.
        alive = st.faults.alive
        last: dict[tuple, Any] = {}
        for c in script:
            if c.name == "write":
                client, key, _ = c.args
                last[(client, key)] = c.args
        # Only writes from clients that stayed correct are constrained
        # (a crashed client cannot receive its ok).
        surviving = {(cl_, k) for (cl_, k) in last if bool(alive[cl_])}
        for (client, key) in surviving:
            if not bool(self.model.acked_ok(st.model, client, key)):
                return False
        for key in {k for (_cl, k) in surviving}:
            if not bool(self.model.replicated(st.model, key, alive)):
                return False
        return True

    def settle_rounds(self) -> int:
        return 15


@dataclasses.dataclass
class AtomicCommitSystem:
    """Application-under-test model (the role test/prop_partisan_hbbft.erl
    :703 plays for the reference — proving the harness hosts a NON-TOY
    system): the atomic-commit ENGINE (models/commit.py — Lampson 2PC,
    Bernstein CTP, Skeen 3PC) under the crash fault model, checked
    against atomic commitment's real safety properties:

    - AGREEMENT: no transaction ends with both a committed and an
      aborted participant (AC1),
    - UNIFORMITY: a coordinator-reported ok implies every alive
      participant delivered (the blocking hole 2PC is famous for — an
      omission in the commit fan-out strands a prepared participant,
      which Bernstein CTP's cooperative termination repairs and plain
      2PC cannot).
    """

    variant: str = "bernstein_ctp"
    n_nodes: int = 6
    slots: int = 4
    seed: int = 1
    name: str = "atomic_commit"

    def __post_init__(self) -> None:
        from partisan_tpu.models.commit import CommitProtocol

        self.model = CommitProtocol(self.variant, slots=self.slots,
                                    coordinator_timeout_rounds=10,
                                    participant_timeout_rounds=5)
        self._next_slot = 0

    def build(self):
        self._next_slot = 0
        return _cached_build(self, lambda: Cluster(
            Config(n_nodes=self.n_nodes, seed=self.seed,
                   inbox_cap=max(32, self.n_nodes + 8)),
            model=self.model))

    def begin_command(self, coord: int, slot: int, value: int) -> Command:
        import jax.numpy as jnp

        members = jnp.ones((self.n_nodes,), jnp.bool_)

        def apply(c, s):
            return s._replace(model=self.model.begin(
                s.model, coord, slot, value, members, s.rnd))

        return Command(name="begin", args=(coord, slot, value), apply=apply)

    def gen_command(self, rng: random.Random, cl, st) -> Command:
        slot = self._next_slot % self.slots
        self._next_slot += 1
        return self.begin_command(rng.randrange(self.n_nodes), slot,
                                  rng.randrange(1, 1000))

    def postcondition(self, cl, st, script) -> bool:
        if not bool(self.model.agreement(st.model)):
            return False
        begun = {c.args[1] for c in script if c.name == "begin"}
        return all(
            bool(self.model.committed_implies_all(
                st.model, slot, st.faults.alive))
            for slot in begun)

    def settle_rounds(self) -> int:
        # covers the coordinator timeout (10) + CTP's decision-request
        # repair cycle after the heal
        return 30


@dataclasses.dataclass
class PaxosSystem:
    """Consensus application-under-test (the prop_partisan_paxoid.erl:385
    role): vectorized single-decree Paxos (models/paxos.py) under the
    crash fault model, with linearizability-grade postconditions:

    - AGREEMENT: across every node (alive or crashed), at most one
      value is ever learned per decree,
    - VALIDITY: a learned value was proposed for that decree,
    - conditional TERMINATION: with a majority alive and partitions
      healed, a decree somebody proposed and some surviving proposer
      still owns must be decided by settle time.

    ``unsafe_adopt`` forwards to the model — it plants the broken
    Synod adoption rule (ignore promises' highest-accepted value) the
    harness must catch and shrink (tests/test_paxos.py).
    """

    n_nodes: int = 5
    slots: int = 2
    seed: int = 3
    quorum: int | None = None
    unsafe_adopt: bool = False
    check_termination: bool = True
    name: str = "paxos"

    def __post_init__(self) -> None:
        from partisan_tpu.models.paxos import Paxos

        self.model = Paxos(slots=self.slots, quorum=self.quorum,
                           retry_rounds=8,
                           unsafe_adopt=self.unsafe_adopt)
        self._next_val = 0

    def build(self):
        return _cached_build(self, lambda: Cluster(
            Config(n_nodes=self.n_nodes, seed=self.seed,
                   msg_words=13,
                   inbox_cap=max(48, 8 * self.n_nodes),
                   emit_cap=16),
            model=self.model))

    def propose_command(self, node: int, slot: int, value: int) -> Command:
        def apply(c, s, _n=node, _sl=slot, _v=value):
            return s._replace(model=self.model.propose(
                s.model, _n, _sl, _v, int(s.rnd), self.n_nodes))

        return Command(name="propose", args=(node, slot, value),
                       apply=apply)

    def gen_command(self, rng: random.Random, cl, st) -> Command:
        self._next_val += 1
        return self.propose_command(rng.randrange(self.n_nodes),
                                    rng.randrange(self.slots),
                                    100 + self._next_val)

    def postcondition(self, cl, st, script) -> bool:
        import numpy as np

        proposed: dict[int, set] = {}
        proposers: dict[int, list] = {}
        for c in script:
            if c.name == "propose":
                node, slot, val = c.args
                proposed.setdefault(slot, set()).add(val)
                proposers.setdefault(slot, []).append(node)
        if not self.model.agreement(st.model):
            return False
        if not self.model.validity(st.model, proposed):
            return False
        if not self.check_termination:
            return True
        alive = np.asarray(st.faults.alive)
        if alive.sum() <= self.n_nodes // 2:
            return True                    # no quorum: liveness waived
        for slot, nodes in proposers.items():
            if any(alive[p] for p in nodes) and \
                    not self.model.decided_nodes(st.model, slot):
                return False
        return True

    def settle_rounds(self) -> int:
        # several retry windows: dueling proposers need a few ballots
        return 60
